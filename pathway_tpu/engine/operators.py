"""Incremental operators over diff-deltas.

Rebuild of the reference engine's operator set (``trait Graph``,
src/engine/graph.rs:664-1007, implemented in src/engine/dataflow.rs). Each
operator consumes consolidated input deltas for one timestamp and emits the
exact output delta — the differential-dataflow contract — but scheduled by a
host-side microbatch loop instead of timely progress tracking. Batched
columnar callables (numpy/XLA) do the per-batch math; there is no per-row
FFI in the hot path.

Conventions:
- every table is keyed: ≤1 live row per key,
- ``step(time, in_deltas)`` is called once per node per timestamp,
- map/filter callables receive ``(keys: list[Pointer], rows: list[tuple])``
  and return batch results (lists / numpy arrays).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np

from pathway_tpu.engine.delta import (
    Arrangement,
    Delta,
    row_fingerprint,
    upsert_delta,
)
from pathway_tpu.engine.reducers import _orderable, make_reducer_state
from pathway_tpu.internals.keys import (Pointer, canonical_shard_value,
                                        hash_values, mix_pointers)


class Exchange:
    """Per-input exchange contracts for sharded execution (reference:
    src/engine/dataflow/shard.rs — keys route to workers by hash; exchange
    pacts on arrange/join/group inputs). A spec is one of:

    - ``None``: no data movement — the input is processed on whichever
      worker currently holds each row (stateless operators),
    - ``Exchange.BY_KEY``: route each entry by its row key,
    - ``Exchange.GATHER``: send everything to worker 0 (operators whose
      state cannot be partitioned, e.g. fixpoint iteration),
    - ``Exchange.BROADCAST``: every worker (and every process, under a
      cluster) sees the complete input delta — the reference's
      ``.broadcast()`` on the external-index data stream
      (operators/external_index.rs:97) and gradual_broadcast's threshold
      stream,
    - a callable ``(key, row) -> routing value``: route by the hash of the
      returned value (join keys, group keys, instances).
    """

    BY_KEY = "by_key"
    GATHER = "gather"
    BROADCAST = "broadcast"


class SnapshotUnsupported(RuntimeError):
    """Raised by ``snapshot_state`` when an operator holds state it cannot
    capture as plain data (e.g. an external index without capture hooks).
    The streaming runtime disables snapshotting for the run — recovery
    falls back to full-WAL replay — instead of writing a checkpoint that
    silently misses state."""


class Operator:
    arity = 1
    # False for ops whose replicas share mutable state (e.g. one device
    # slab): their per-worker steps must not run on the thread pool
    parallel_safe = True
    # True for ops whose step dispatches accelerator work (device-resident
    # index add/search, traceable batch UDFs): with n_workers == 1 and
    # PATHWAY_DEVICE_INFLIGHT >= 2 the scheduler defers this op AND its
    # downstream closure to the device bridge so the next tick's host work
    # overlaps the dispatch (engine/device_bridge.py)
    device_bound = False
    # Consulted only for EXCHANGED inputs (the sharded merge points in
    # graph.py; spec-None inputs always pass through unmerged): False for
    # ops whose step() is exact on unconsolidated input — purely additive
    # state, or exact handling of same-tick insert/retract pairs. Ops
    # whose outputs feed sinks unfused (net-zero pairs would surface as
    # phantom events) keep the default.
    consolidate_inputs = True

    def step(self, time: int, in_deltas: list[Delta]) -> Delta:
        raise NotImplementedError

    def exchange_specs(self) -> list:
        """One exchange spec per input (see Exchange). Default: stateless —
        rows are processed wherever they already live."""
        return [None] * self.arity

    def replicate(self, n: int) -> list["Operator"]:
        """Return n worker replicas of this operator, self as worker 0.

        Must be called before any data has flowed (state empty), so a
        deepcopy clones configuration (closures are shared by reference —
        the copy module treats functions as atomic) with fresh state.
        """
        import copy

        return [self] + [copy.deepcopy(self) for _ in range(n - 1)]

    def on_time_advance(self, time: int) -> Delta:
        """Called for every committed timestamp (even with no input) so
        buffering operators (temporal behaviors) can release rows."""
        return Delta()

    def flush(self, time: int) -> Delta:
        """End-of-stream: release anything still held (the reference flushes
        buffers when the input frontier reaches +inf, operators/time_column.rs).
        Only called once, at the final flush tick."""
        return Delta()

    # -- operator-state checkpoints (engine/persistence.py snapshots) ------
    def snapshot_state(self):
        """Plain-data capture of this operator's accumulated state, or
        ``None`` for stateless operators (the default). The returned value
        must decode under the persistence layer's restricted unpickler:
        containers, scalars, ndarrays, Pointers — never classes or
        callables. Called by the Scheduler at a snapshot tick, with every
        device leg <= that tick resolved (state is a consistent cut).
        Raise :class:`SnapshotUnsupported` for state that cannot be
        captured — the runtime then disables snapshots loudly."""
        return None

    def restore_state(self, state) -> None:
        """Inverse of :meth:`snapshot_state`, called on a freshly-built
        operator before any data flows."""
        raise SnapshotUnsupported(
            f"{type(self).__name__} recorded no snapshot hook but a "
            "snapshot carries state for it — the graph changed between "
            "runs, or the snapshot is foreign")


class SourceOperator(Operator):
    """Fed externally by an input session; just passes its delta through."""

    arity = 0

    def __init__(self, name: str = "source"):
        self.name = name
        self.pending = Delta()

    def push(self, delta: Delta) -> None:
        self.pending.extend(delta.entries)

    def step(self, time, in_deltas):
        # consolidation here is load-bearing: a same-batch net-zero
        # (key,row) pair must cancel BEFORE operators/sinks see it —
        # order-sensitive reducers would otherwise record deleted values,
        # float sums drift, and sinks emit phantom insert/delete events
        out = self.pending.consolidate()
        self.pending = Delta()
        return out


class MapOperator(Operator):
    """Row-wise (batched) projection: select / expression tables
    (reference: expression_table, dataflow.rs:1258)."""

    def __init__(self, fn: Callable[[list, list], list]):
        self.fn = fn

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        keys = delta.keys_list()
        rows = [r for _, r, _ in delta.entries]
        new_rows = self.fn(keys, rows)
        # contract: fn returns one TUPLE per row (compile_program and the
        # lowering's projections all do) — re-tupling was pure overhead
        return Delta([
            (k, nr, d)
            for (k, _, d), nr in zip(delta.entries, new_rows)
        ])


class ZipAlignedOperator(Operator):
    """Stateless zip of two 1:1 projections of the SAME upstream delta.

    Built by the lowering's auto-jit host/device map split
    (internals/runner.py): both inputs are MapOperators over one input
    node, so each tick they emit the same keys with the same diffs in the
    same order — the recombination needs no arrangements, just a
    positional merge per the column spec ((side, pos), ...) with side 0 =
    left row, 1 = right row. Alignment is asserted, not assumed: a key or
    diff mismatch means an engine invariant broke, and wrong-but-plausible
    output would be strictly worse than a crash."""

    arity = 2

    def __init__(self, spec: tuple):
        self.spec = tuple(spec)
        # the merge runs per row on the hot path: compile it once to a
        # C-level tuple build instead of interpreting the spec per cell
        cells = ", ".join(f"{'l' if side == 0 else 'r'}[{pos}]"
                          for side, pos in self.spec)
        self._combine = eval(  # noqa: S307 — generated from the int spec
            f"lambda l, r: ({cells}{',' if self.spec else ''})")

    def step(self, time, in_deltas):
        dl, dr = in_deltas
        if not dl and not dr:
            return Delta()
        if len(dl.entries) != len(dr.entries):
            raise RuntimeError(
                "auto-jit map split lost alignment: "
                f"{len(dl.entries)} host rows vs {len(dr.entries)} device "
                "rows in one tick")
        combine = self._combine
        out = []
        for (lk, lrow, ld), (rk, rrow, rd) in zip(dl.entries, dr.entries):
            if lk != rk or ld != rd:
                raise RuntimeError(
                    "auto-jit map split lost alignment: "
                    f"({lk!r}, {ld}) vs ({rk!r}, {rd})")
            out.append((lk, combine(lrow, rrow), ld))
        return Delta(out)


def _stable_row_fp(row: tuple) -> int:
    """Cross-process-stable row digest (hash_values: fixed blake2b salt)
    for cache keys that must survive a snapshot restore into a NEW
    interpreter — hash()-based row_fingerprint varies with the process
    hash seed for string cells. Costlier than hash() per novel row
    (hash_values memoizes repeats), but this keys only
    DeterministicMapOperator, which exists to cache NON-deterministic
    user fns — a path already dominated by the fn call itself; re-keying
    at restore (the cheaper pattern used for multiset reducers) is
    impossible here because the cache does not retain input rows."""
    return int(hash_values(*row))


class DeterministicMapOperator(MapOperator):
    """Map that caches outputs per key so retractions replay identical values
    even for non-deterministic fns (reference:
    map_named_with_consistent_deletions, dataflow/operators.rs:308)."""

    def __init__(self, fn):
        super().__init__(fn)
        self.cache: dict[tuple[Pointer, int], tuple] = {}

    def snapshot_state(self):
        # the cache IS semantics: retractions after restore must replay
        # the exact values the non-deterministic fn produced pre-crash.
        # Keys use the stable fingerprint, so they survive as-is.
        return {"cache": self.cache}

    def restore_state(self, state) -> None:
        self.cache = dict(state["cache"])

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        out = Delta()
        to_eval = []
        for key, row, diff in delta.entries:
            ck = (key, _stable_row_fp(row))
            if diff < 0 and ck in self.cache:
                out.append(key, self.cache.pop(ck), diff)
            else:
                to_eval.append((key, row, diff, ck))
        if to_eval:
            keys = [k for k, _, _, _ in to_eval]
            rows = [r for _, r, _, _ in to_eval]
            new_rows = self.fn(keys, rows)
            for (key, _, diff, ck), nr in zip(to_eval, new_rows):
                nr = tuple(nr)
                if diff > 0:
                    self.cache[ck] = nr
                out.append(key, nr, diff)
        return out


class FilterOperator(Operator):
    def __init__(self, pred: Callable[[list, list], Sequence[bool]]):
        self.pred = pred

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        keys = delta.keys_list()
        rows = [r for _, r, _ in delta.entries]
        mask = self.pred(keys, rows)
        return Delta([e for e, m in zip(delta.entries, mask) if m])


class ReindexOperator(Operator):
    """Re-key rows (with_id_from / reindex). New key computed from the row;
    collisions on the new key are a user error (like reference)."""

    def __init__(self, key_fn: Callable[[list, list], Sequence[Pointer]]):
        self.key_fn = key_fn

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        keys = delta.keys_list()
        rows = [r for _, r, _ in delta.entries]
        new_keys = self.key_fn(keys, rows)
        return Delta([
            (nk, r, d) for (k, r, d), nk in zip(delta.entries, new_keys)
        ]).consolidate()


class FlattenOperator(Operator):
    """One row -> many rows (Table.flatten). fn(key,row) yields (new_key,new_row)."""

    def __init__(self, fn: Callable[[Pointer, tuple], list]):
        self.fn = fn

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta()
        for key, row, diff in delta.entries:
            for nk, nr in self.fn(key, row):
                out.append(nk, tuple(nr), diff)
        return out.consolidate()


class BinaryKeyOperator(Operator):
    """Generic key-aligned binary combiner.

    Covers concat/update_rows/intersect/difference/restrict/having and
    same-universe column zipping: maintains both input arrangements, and for
    every affected key recomputes ``combine(left_row|None, right_row|None)``
    before and after the delta, emitting the difference. This is the
    host analogue of DD's arrange-both-sides + per-key recompute
    (reference: concat/update_rows via engine union ops, dataflow.rs).
    """

    arity = 2

    def __init__(self, combine: Callable[[tuple | None, tuple | None], tuple | None]):
        self.combine = combine
        self.left = Arrangement()
        self.right = Arrangement()

    def exchange_specs(self):
        return [Exchange.BY_KEY, Exchange.BY_KEY]

    def snapshot_state(self):
        return {"left": self.left.rows, "right": self.right.rows}

    def restore_state(self, state) -> None:
        self.left.rows = dict(state["left"])
        self.right.rows = dict(state["right"])

    def step(self, time, in_deltas):
        dl, dr = in_deltas
        if not dl and not dr:
            return Delta()
        affected: dict[Pointer, None] = {}
        for k, _, _ in dl.entries:
            affected[k] = None
        for k, _, _ in dr.entries:
            affected[k] = None
        old_out: dict[Pointer, tuple | None] = {}
        for k in affected:
            old_out[k] = self.combine(self.left.get(k), self.right.get(k))
        self.left.update(dl)
        self.right.update(dr)
        out = Delta()
        for k in affected:
            new = self.combine(self.left.get(k), self.right.get(k))
            old = old_out[k]
            if old is not None and (new is None or
                                    row_fingerprint(old) != row_fingerprint(new)):
                out.append(k, old, -1)
            if new is not None and (old is None or
                                    row_fingerprint(old) != row_fingerprint(new)):
                out.append(k, new, 1)
        return out


class NAryConcatOperator(Operator):
    """Disjoint-key union of N inputs (Table.concat). Raises on key overlap
    unless ``update`` (last input wins — update_rows semantics)."""

    def __init__(self, n: int, combine_rows: Callable[[list], tuple | None],
                 update: bool = False):
        self.arity = n
        self.states = [Arrangement() for _ in range(n)]
        self.combine_rows = combine_rows
        self.update = update

    def exchange_specs(self):
        return [Exchange.BY_KEY] * self.arity

    def snapshot_state(self):
        return {"states": [st.rows for st in self.states]}

    def restore_state(self, state) -> None:
        for st, rows in zip(self.states, state["states"]):
            st.rows = dict(rows)

    def step(self, time, in_deltas):
        if not any(in_deltas):
            return Delta()
        affected: dict[Pointer, None] = {}
        for d in in_deltas:
            for k, _, _ in d.entries:
                affected[k] = None
        old = {k: self._combined(k) for k in affected}
        for st, d in zip(self.states, in_deltas):
            st.update(d)
        out = Delta()
        for k in affected:
            new = self._combined(k)
            o = old[k]
            if o is not None and (new is None or row_fingerprint(o) != row_fingerprint(new)):
                out.append(k, o, -1)
            if new is not None and (o is None or row_fingerprint(o) != row_fingerprint(new)):
                out.append(k, new, 1)
        return out

    def _combined(self, key):
        present = [st.get(key) for st in self.states]
        live = [r for r in present if r is not None]
        if not live:
            return None
        if len(live) > 1 and not self.update:
            raise KeyError(
                f"duplicate key {key!r} in concat of tables with overlapping "
                "universes; use update_rows or concat_reindex"
            )
        return self.combine_rows(present)


_ARRAY_SUM_DEVICE_MIN: int | None = None
# ticks smaller than this skip the device pre-pass outright (not worth
# the per-entry extract scan); tests lower it to exercise sharded runs
_ARRAY_SUM_MIN_ROWS = 64


def _array_sum_device_min() -> int:
    """Element-count threshold above which a tick's array_sum rows route
    through the XLA segment-sum kernel instead of per-row numpy adds
    (PATHWAY_ARRAY_SUM_DEVICE_MIN; 0 disables the device path)."""
    global _ARRAY_SUM_DEVICE_MIN
    if _ARRAY_SUM_DEVICE_MIN is None:
        import os

        _ARRAY_SUM_DEVICE_MIN = int(os.environ.get(
            "PATHWAY_ARRAY_SUM_DEVICE_MIN", 1 << 20))
    return _ARRAY_SUM_DEVICE_MIN


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


_SEGSUM_FN = None


def _device_segsum_fn():
    """Jitted sequential segment-sum: input (G, M, D) of diff-weighted
    rows (row m of group g, zero-padded past the group's length), output
    (G, D) per-group totals.

    The reduction is a ``lax.scan`` over the M axis — per group, rows
    accumulate one at a time IN ORDER, exactly like the per-row numpy
    path (``total = total + diff * v``). Zero padding is exact under IEEE
    addition, so the result is BITWISE-identical to the sequential host
    loop — the device path does not weaken the n_workers ∈ {1, N}
    byte-identity contract the lowering's canonical sort establishes.
    (A plain one-hot matmul or ``segment_sum`` would be faster but
    reassociates the adds, making results depend on batch shape.)
    """
    global _SEGSUM_FN
    if _SEGSUM_FN is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def segsum(padded, init):
            def body(acc, rows):
                return acc + rows, None

            acc, _ = jax.lax.scan(body, init,
                                  jnp.moveaxis(padded, 1, 0))
            return acc

        _SEGSUM_FN = segsum
    return _SEGSUM_FN


class GroupByOperator(Operator):
    """groupby().reduce() (reference: group_by_table, dataflow.rs:2904).

    ``group_fn(key,row) -> (group_key, group_vals)`` routes each input row to
    a group; ``reducer_specs`` is a list of
    ``(name, extract(key,row)->argtuple, kwargs)``. Emits per changed group a
    retraction of the old reduced row and the new one.
    """

    def __init__(self, group_fn, reducer_specs,
                 force_order_sensitive: bool = False):
        self.group_fn = group_fn
        self.reducer_specs = reducer_specs
        self.group_states: dict[Pointer, list] = {}   # gkey -> [states...]
        self.group_vals: dict[Pointer, tuple] = {}
        self.group_counts: dict[Pointer, int] = {}    # membership multiset size
        self.out = Arrangement()
        self.seq = 0
        # all other reducers are commutative multisets/semigroups — the
        # canonical sort below is pure overhead for them. The lowering
        # forces the sort for float sums (addition not associative: the
        # n_workers ∈ {1, N} identity contract needs a canonical order)
        self._order_sensitive = force_order_sensitive or any(
            name in ("earliest", "latest", "stateful")
            for name, _, _ in reducer_specs)
        # "sum" included: an ndarray-typed column summed via the plain
        # sum() reducer hits the same device path (the first-row probe
        # rejects scalar sums cheaply)
        self._array_sum_idx = [i for i, (name, _, _)
                               in enumerate(reducer_specs)
                               if name in ("array_sum", "sum")]

    def _device_array_sums(self, entries, routed):
        """Per-tick batched array_sum: one XLA dispatch per reducer for
        the whole tick instead of one numpy add per row (the reference
        keeps ndarray values on the CPU engine, src/engine/reduce.rs
        ArraySum; a TPU-first engine routes embedding-sized columns
        through the device). Returns {reducer_idx: {gkey: (total, count)}}
        for the reducers it handled; unhandled ones (mixed shapes,
        non-f32 dtypes, too small to pay for a dispatch) fall back to the
        per-row path."""
        threshold = _array_sum_device_min()
        if threshold <= 0:
            return {}
        handled: dict[int, dict] = {}
        for idx in self._array_sum_idx:
            name, extract, _kw = self.reducer_specs[idx]
            # probe the first row before scanning the whole tick: the
            # element count is already decidable from one row's shape
            first = np.asarray(extract(*entries[0][:2])[0])
            shape = first.shape
            if not shape:
                # scalar sum() column: the per-row path returns np.float32
                # scalars; the device path would emit 0-d ndarrays and the
                # output column's type would depend on tick size
                continue
            d = int(np.prod(shape))
            if first.dtype != np.float32 or len(entries) * d < threshold:
                continue
            arrs = [first]
            ok = True
            for key, row, _diff in entries[1:]:
                a = np.asarray(extract(key, row)[0])
                if a.dtype != np.float32 or a.shape != shape:
                    ok = False
                    break
                arrs.append(a)
            if not ok:
                continue
            try:
                import jax.numpy as jnp
            except Exception:  # pragma: no cover - jax always present
                return {}
            # rows per group, in entry order (canonically sorted by the
            # caller when float — accumulation order is part of the
            # byte-identity contract)
            group_rows: dict[Pointer, list[int]] = {}
            counts: dict[Pointer, int] = {}
            for i, (key, row, diff) in enumerate(entries):
                gkey = routed[i][0]
                group_rows.setdefault(gkey, []).append(i)
                counts[gkey] = counts.get(gkey, 0) + diff
            gkeys = list(group_rows)
            # a prior running total that is not float32 (e.g. float64 rows
            # accumulated by earlier small ticks) must keep its dtype —
            # fall back to the per-row path for this reducer
            priors = {}
            ok = True
            for gkey in gkeys:
                states = self.group_states.get(gkey)
                prior = states[idx].total if states is not None else None
                if prior is not None:
                    prior = np.asarray(prior)
                    if prior.dtype != np.float32 or prior.shape != shape:
                        ok = False
                        break
                priors[gkey] = prior
            if not ok:
                continue
            m_b = _next_pow2(max(len(v) for v in group_rows.values()))
            g_b = _next_pow2(len(gkeys))
            # pad with -0.0, the exact IEEE additive identity
            # (x + -0.0 == x bitwise for every x INCLUDING -0.0, whereas
            # x + 0.0 flips a -0.0 total to +0.0) — padding and seeding
            # must not perturb the byte-identity contract
            padded = np.full((g_b, m_b, d), -0.0, dtype=np.float32)
            # seed the scan with each group's RUNNING total: the kernel
            # then continues the exact sequential accumulation
            # ((T + v_a) + v_b), not T + (v_a + v_b) — reassociating
            # across the tick boundary would drift from the numpy path.
            # Fresh-group seed mirrors each state's numpy start exactly:
            # _ArraySumState begins at diff*v (no addition — seed -0.0,
            # the identity), _SumState begins at int 0 + diff*v (seed
            # +0.0, so a -0.0 first value flips to +0.0 as numpy does)
            fresh_zero = np.float32(-0.0 if name == "array_sum" else 0.0)
            init = np.full((g_b, d), fresh_zero, dtype=np.float32)
            for g, gkey in enumerate(gkeys):
                if priors[gkey] is not None:
                    init[g] = priors[gkey].reshape(-1)
                for p, i in enumerate(group_rows[gkey]):
                    diff = entries[i][2]
                    row_vec = arrs[i].reshape(-1)
                    padded[g, p] = row_vec if diff == 1 else diff * row_vec
            totals = np.asarray(_device_segsum_fn()(
                jnp.asarray(padded), jnp.asarray(init)))
            handled[idx] = {
                gkey: (totals[g].reshape(shape), counts[gkey])
                for g, gkey in enumerate(gkeys)}
        return handled

    def exchange_specs(self):
        # route rows to the worker owning their group (reference: group_by
        # exchanges by group key, dataflow.rs:2904)
        return [lambda key, row: self.group_fn(key, row)[0]]

    def snapshot_state(self):
        return {
            "groups": {gkey: [st.state_dict() for st in states]
                       for gkey, states in self.group_states.items()},
            "vals": self.group_vals,
            "counts": self.group_counts,
            "out": self.out.rows,
            "seq": self.seq,
        }

    def restore_state(self, state) -> None:
        self.group_states = {}
        for gkey, dicts in state["groups"].items():
            states = [make_reducer_state(name, **kw)
                      for name, _, kw in self.reducer_specs]
            for st, d in zip(states, dicts):
                st.load_state(d)
            self.group_states[gkey] = states
        self.group_vals = dict(state["vals"])
        self.group_counts = dict(state["counts"])
        self.out.rows = dict(state["out"])
        self.seq = state["seq"]

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        touched: dict[Pointer, None] = {}
        # canonical per-tick order (key, then retractions-first, then row):
        # order-sensitive reducers (earliest/latest stamps, stateful folds)
        # must not depend on arrival order, which sharded exchange permutes —
        # with a canonical order, n_workers ∈ {1, N} give identical results
        if self._order_sensitive:
            entries = sorted(
                delta.entries,
                key=lambda e: (int(e[0]), e[2], row_fingerprint(e[1])))
        else:
            entries = delta.entries
        routed = None
        device_sums: dict[int, dict] = {}
        if self._array_sum_idx and len(entries) >= _ARRAY_SUM_MIN_ROWS:
            routed = [self.group_fn(key, row) for key, row, _ in entries]
            device_sums = self._device_array_sums(entries, routed)
        for i, (key, row, diff) in enumerate(entries):
            gkey, gvals = routed[i] if routed is not None \
                else self.group_fn(key, row)
            states = self.group_states.get(gkey)
            if states is None:
                states = [make_reducer_state(name, **kw)
                          for name, _, kw in self.reducer_specs]
                self.group_states[gkey] = states
                self.group_vals[gkey] = gvals
                self.group_counts[gkey] = 0
            self.group_counts[gkey] += diff
            for ri, (st, (name, extract, _kw)) in enumerate(
                    zip(states, self.reducer_specs)):
                if ri in device_sums:
                    continue  # whole tick pre-summed on device below
                args = extract(key, row)
                if name in ("earliest", "latest"):
                    if diff > 0:
                        args = (*args, (time, self.seq))
                        self.seq += 1
                    else:
                        args = (*args, None)
                st.add(args, diff)
            touched[gkey] = None
        for ri, per_group in device_sums.items():
            for gkey, (total, count) in per_group.items():
                self.group_states[gkey][ri].set_total(total, count)
        out = Delta()
        for gkey in touched:
            states = self.group_states[gkey]
            if self.group_counts.get(gkey, 0) <= 0:
                new_row = None
                del self.group_states[gkey]
                self.group_vals.pop(gkey, None)
                self.group_counts.pop(gkey, None)
            else:
                gvals = self.group_vals[gkey]
                new_row = (*gvals, *[st.emit() for st in states])
            upsert_delta(self.out, gkey, new_row, out)
        self.out.update(out)
        return out


_FASTJOIN = False  # False = not probed, None = unavailable, module = loaded
_FASTGROUP = False


def _get_fastjoin():
    """Native inner-join pass (native/fastjoin.cpp), built on first use;
    None when the toolchain is unavailable (pure-Python fallback)."""
    global _FASTJOIN
    if _FASTJOIN is False:
        try:
            from pathway_tpu.native.build import load_extension

            _FASTJOIN = load_extension("fastjoin")
        except Exception as e:
            import logging

            logging.getLogger("pathway_tpu").warning(
                "native join fast path unavailable (%s); using the "
                "pure-Python engine loops", e)
            _FASTJOIN = None
    return _FASTJOIN


def _get_fastgroup():
    """Native groupby gather/emit passes (native/fastgroup.cpp)."""
    global _FASTGROUP
    if _FASTGROUP is False:
        try:
            from pathway_tpu.native.build import load_extension

            _FASTGROUP = load_extension("fastgroup")
        except Exception as e:
            import logging

            logging.getLogger("pathway_tpu").warning(
                "native groupby fast path unavailable (%s); using the "
                "pure-Python engine loops", e)
            _FASTGROUP = None
    return _FASTGROUP


def _rows_equal(a, b) -> bool:
    """Value equality of two rows; fingerprint fallback for rows whose
    cells don't support plain == (ndarrays)."""
    try:
        return bool(a == b)
    except Exception:
        return row_fingerprint(a) == row_fingerprint(b)


class ColumnarGroupByOperator(Operator):
    """Columnar groupby for dictionary-encodable group keys with
    semigroup-sum reducers (count / integral sum / integral avg).

    The row path (GroupByOperator) pays per-row Python: a 128-bit hash per
    row for the group key plus a dict probe and a state-object method call
    per reducer. Here a tick's delta is processed as arrays: group values
    are interned to dense int codes (one dict probe per row, no hashing —
    the group key is hashed ONCE per distinct group ever seen), reducer
    state lives in numpy int64 arrays indexed by code (``np.add.at``
    scatter), and only the touched groups pay per-group Python at emit.
    Exact-retraction semantics are unchanged: all state updates are
    additive, so arbitrary insert/retract orders give identical state.

    Chosen by the lowering only when every reducer is in the columnar set,
    no reducer is order-sensitive, and the group values come from plain
    columns of hashable scalar dtype (internals/runner.py
    ``_columnar_groupby_spec``); everything else keeps GroupByOperator.
    Reference analogue: group_by_table (src/engine/dataflow.rs:2904).
    """

    _GROW = 1024
    _INT_GUARD = 1 << 62  # |sum| beyond this migrates to exact python ints
    consolidate_inputs = False  # purely additive array state

    # derived interning tables (typed-key and hashed-key -> dense code):
    # deliberately outside the snapshot — restore_state rebuilds them
    # from _gvals/_gkeys exactly as _codes constructs them, so the
    # coverage sanitizer must not demand their capture
    _snapshot_sanitizer_exempt = ("_intern", "_by_gkey")

    def __init__(self, gval_pos: list, reducer_cols: list):
        # gval_pos: row positions of the group-value columns
        # reducer_cols: [("count", None) | ("sum"|"avg"|"min"|"max", pos)]
        self.gval_pos = list(gval_pos)
        self.reducer_cols = list(reducer_cols)
        # (slot, code) -> exact python-int total for groups whose sums
        # left the int64 guard range (row-path _SumState is bigint-exact)
        self._big: dict = {}
        self._intern: dict = {}          # typed gval -> dense code
        self._by_gkey: dict = {}         # hashed gkey -> code (alias dedup)
        self._gvals: list[tuple] = []    # code -> group values
        self._gkeys: list[Pointer] = []  # code -> output key (hashed once)
        self._last: list = []            # code -> last emitted row | None
        self._cnt = np.zeros(0, np.int64)
        # value-bearing reducers share one extraction slot order (the C
        # gather returns one column per _val_pos entry; -1 extracts the
        # row key); sums/avgs additionally own an int64 state array,
        # min/max/argmin/argmax a per-group value-count multiset (exact
        # under retraction)
        self._val_slot: dict[int, int] = {}   # reducer -> cmp/value slot
        self._arg_slot: dict[int, int] = {}   # argminmax -> payload slot
        self._sum_slot: dict[int, int] = {}
        self._mm: dict[int, dict] = {}   # reducer idx -> {code: {val: n}}
        val_pos: list[int] = []
        for i, (kind, pos) in enumerate(reducer_cols):
            if kind == "count":
                continue
            if kind in ("argmin", "argmax"):
                cpos, ppos = pos
                self._val_slot[i] = len(val_pos)
                val_pos.append(cpos)
                self._arg_slot[i] = len(val_pos)
                val_pos.append(ppos)
                self._mm[i] = {}
                continue
            self._val_slot[i] = len(val_pos)
            val_pos.append(pos)
            if kind in ("sum", "avg"):
                self._sum_slot[i] = len(self._sum_slot)
            else:  # min / max
                self._mm[i] = {}
        self._sums = [np.zeros(0, np.int64) for _ in self._sum_slot]
        # native-pass parameter tables (see native/fastgroup.cpp)
        self._gp = tuple(self.gval_pos)
        self._val_pos = tuple(val_pos)
        self._kinds = tuple(
            0 if kind == "count" else (2 if kind == "avg" else 1)
            for kind, _ in reducer_cols)

    def exchange_specs(self):
        # route by the CANONICAL group value: the scheduler's route cache
        # memoizes value -> worker (a dict probe instead of a hash per
        # row), and canonicalization guarantees hash-equal values (1 vs
        # 1.0 vs np.int64(1) — which _add_group aliases into one group)
        # land on the same worker. Tuples route through hash_values, whose
        # encoding collapses the same equivalences element-wise.
        if len(self.gval_pos) == 1:
            p = self.gval_pos[0]
            return [lambda key, row: canonical_shard_value(row[p])]
        ps = self.gval_pos
        return [lambda key, row: tuple(row[p] for p in ps)]

    def snapshot_state(self):
        n = len(self._gvals)
        return {
            "gvals": self._gvals,
            "gkeys": self._gkeys,
            "last": self._last,
            "cnt": self._cnt[:n].copy(),
            "sums": [s[:n].copy() for s in self._sums],
            "big": self._big,
            "mm": self._mm,
        }

    def restore_state(self, state) -> None:
        self._gvals = [tuple(g) for g in state["gvals"]]
        self._gkeys = list(state["gkeys"])
        self._last = list(state["last"])
        n = len(self._gvals)
        self._cnt = np.asarray(state["cnt"], np.int64).copy()
        self._sums = [np.asarray(s, np.int64).copy() for s in state["sums"]]
        self._big = dict(state["big"])
        for i in self._mm:
            self._mm[i] = {c: dict(g)
                           for c, g in state["mm"].get(i, {}).items()}
        # the interning tables hold CLASS objects (typed keys) — never
        # serialized; rebuilt from the group values exactly as _codes
        # constructs them
        self._intern = {}
        self._by_gkey = {}
        for code in range(n):
            gvals = self._gvals[code]
            self._by_gkey[self._gkeys[code]] = code
            if len(self.gval_pos) == 1:
                v = gvals[0]
                tk = (v.__class__, v)
            else:
                tk = (tuple(v.__class__ for v in gvals), gvals)
            self._intern[tk] = code

    def _add_group(self, tkey, gvals: tuple) -> int:
        # alias via the hashed key: distinct typed representations of
        # hash-equal values (1 vs 1.0, np.int64(5) vs 5) must share a
        # group, exactly as the row path's hash_values keying does
        gkey = hash_values(*gvals)
        code = self._by_gkey.get(gkey)
        if code is not None:
            self._intern[tkey] = code
            return code
        code = len(self._gvals)
        self._intern[tkey] = code
        self._by_gkey[gkey] = code
        self._gvals.append(gvals)
        self._gkeys.append(gkey)
        self._last.append(None)
        if code >= self._cnt.shape[0]:
            self._cnt = np.concatenate(
                [self._cnt, np.zeros(self._GROW, np.int64)])
            self._sums = [np.concatenate([s, np.zeros(self._GROW, np.int64)])
                          for s in self._sums]
        return code

    def _codes(self, entries) -> np.ndarray:
        intern = self._intern
        get = intern.get
        add = self._add_group
        codes = np.empty(len(entries), np.int64)
        if len(self.gval_pos) == 1:
            p = self.gval_pos[0]
            for i, (_k, row, _d) in enumerate(entries):
                v = row[p]
                # typed key: bool-vs-int dict equality (True == 1) must not
                # merge groups the hash path keeps distinct
                tk = (v.__class__, v)
                c = get(tk)
                codes[i] = add(tk, (v,)) if c is None else c
        else:
            ps = self.gval_pos
            for i, (_k, row, _d) in enumerate(entries):
                gvals = tuple(row[p] for p in ps)
                tk = (tuple(v.__class__ for v in gvals), gvals)
                c = get(tk)
                codes[i] = add(tk, gvals) if c is None else c
        return codes

    def step(self, time, in_deltas):
        entries = in_deltas[0].entries
        if not entries:
            return Delta()
        n = len(entries)
        fg = _get_fastgroup()
        cols = None
        if fg is not None:
            codes_l, diffs_l, cols = fg.gather(
                entries, self._intern, self._add_group, self._gp,
                self._val_pos)
            codes = np.asarray(codes_l, np.int64)
            diffs = np.asarray(diffs_l, np.int64)
        else:
            codes = self._codes(entries)
            diffs = np.fromiter((e[2] for e in entries), np.int64, n)
        np.add.at(self._cnt, codes, diffs)
        touched = np.unique(codes)
        guard = self._INT_GUARD
        # min/max/argmin/argmax multisets: one dict update per entry
        # (exact retraction)
        for i, groups in self._mm.items():
            kind, pos = self.reducer_cols[i]
            if kind in ("argmin", "argmax"):
                cpos, ppos = pos
                if cols is not None:
                    cvals = cols[self._val_slot[i]]
                    pvals = cols[self._arg_slot[i]]
                else:
                    cvals = [e[1][cpos] for e in entries]
                    pvals = [e[0] if ppos < 0 else e[1][ppos]
                             for e in entries]
                vals = list(zip(cvals, pvals))
            else:
                vals = cols[self._val_slot[i]] if cols is not None else \
                    [e[1][pos] for e in entries]
            for c, v, d in zip(codes.tolist(), vals, diffs.tolist()):
                g = groups.get(c)
                if g is None:
                    g = groups[c] = {}
                nc = g.get(v, 0) + d
                if nc == 0:
                    del g[v]
                else:
                    g[v] = nc
        for i, slot in self._sum_slot.items():
            pos = self.reducer_cols[i][1]
            arr = self._sums[slot]
            vals = cols[self._val_slot[i]] if cols is not None else \
                [e[1][pos] for e in entries]
            try:
                col = np.asarray(vals, np.int64)
                # bound the whole tick's contribution so the int64 scatter
                # cannot wrap before the migration check runs
                fast = bool(np.abs(col).max(initial=0) < guard // (n + 1))
            except (TypeError, ValueError, OverflowError):
                fast = False  # None / non-int / giant cells
            if fast:
                np.add.at(arr, codes, col * diffs)
                if self._big:
                    # groups already migrated to exact python ints track
                    # their tick contribution here (their arr slot is dead)
                    big = self._big
                    for j, c in enumerate(codes.tolist()):
                        bk = (slot, c)
                        cur = big.get(bk)
                        if cur is not None:
                            big[bk] = cur + int(col[j]) * int(diffs[j])
                # inputs bounded by the guard and prior totals inside it,
                # so no wrap happened yet; migrate any group that just
                # left the guard range to exact python-int accumulation
                mx = self._sums[slot][touched]
                if np.abs(mx).max(initial=0) >= guard:
                    for c in touched[np.abs(mx) >= guard].tolist():
                        self._big.setdefault((slot, c), int(arr[c]))
            else:
                # exact slow path (mirrors _SumState: bigint, None adds
                # nothing); groups cross into _big when they outgrow int64
                big = self._big
                for c, v, d in zip(codes.tolist(), vals, diffs.tolist()):
                    if v is None:
                        continue
                    bk = (slot, c)
                    cur = big.get(bk)
                    if cur is not None:
                        big[bk] = cur + d * int(v)
                        continue
                    total = int(arr[c]) + d * int(v)
                    if -guard < total < guard:
                        arr[c] = total
                    else:
                        big[bk] = total
        # emit: gather touched-group state as C-batched lists, then one
        # pass over touched groups only (native when available)
        tl = touched.tolist()
        cnts = self._cnt[touched].tolist()
        pcols = []
        for i, (kind, _pos) in enumerate(self.reducer_cols):
            if kind == "count":
                pcols.append([])
            elif kind in ("min", "max"):
                groups = self._mm[i]
                agg = min if kind == "min" else max

                def mm_of(c, _g=groups, _agg=agg):
                    g = _g.get(c)
                    if not g:
                        return None
                    # net-negative counts (a retraction seen ahead of its
                    # insertion) are excluded, matching the row path's
                    # _MultisetState.iter_args max(c, 0) semantics
                    live = [v for v, cnt in g.items() if cnt > 0]
                    return _agg(live) if live else None

                pcols.append([mm_of(c) for c in tl])
            elif kind in ("argmin", "argmax"):
                groups = self._mm[i]
                agg = min if kind == "argmin" else max

                def am_of(c, _g=groups, _agg=agg):
                    g = _g.get(c)
                    if not g:
                        return None
                    # ties break by orderable payload, exactly the row
                    # path's _ArgMin/_ArgMaxState key functions
                    best = _agg(
                        ((cv, _orderable(pv), pv)
                         for (cv, pv), cnt in g.items() if cnt > 0),
                        default=None)
                    return best[2] if best is not None else None

                pcols.append([am_of(c) for c in tl])
            else:
                pcols.append(
                    self._sums[self._sum_slot[i]][touched].tolist())
        big = self._big
        if big:
            for i, (kind, _pos) in enumerate(self.reducer_cols):
                if kind not in ("sum", "avg"):
                    continue
                slot = self._sum_slot[i]
                col = pcols[i]
                for idx, c in enumerate(tl):
                    exact = big.get((slot, c))
                    if exact is not None:
                        col[idx] = exact
        if fg is not None:
            out = Delta()
            out.entries = fg.emit(tl, cnts, self._kinds, pcols,
                                  self._gvals, self._gkeys, self._last)
            return out
        out = Delta()
        append = out.entries.append
        last = self._last
        gkeys = self._gkeys
        gvals = self._gvals
        for idx, code in enumerate(tl):
            c = cnts[idx]
            if c <= 0:
                new = None
            else:
                red = [c if kind == "count"
                       else (pcols[i][idx] / c if kind == "avg"
                             else pcols[i][idx])
                       for i, (kind, _p) in enumerate(self.reducer_cols)]
                new = (*gvals[code], *red)
            old = last[code]
            if old == new:
                continue
            gkey = gkeys[code]
            if old is not None:
                append((gkey, old, -1))
            if new is not None:
                append((gkey, new, 1))
            last[code] = new
        return out


class JoinOperator(Operator):
    """Inner/left/right/outer join (reference: join_tables, dataflow.rs:2276).

    Exact on unconsolidated input: upserts and absent-row retractions are
    handled entry by entry, and a same-tick net-zero pair emits output
    pairs that cancel downstream.

    ``lkey_fn/rkey_fn`` extract the join key from a row; output id =
    hash(join-side ids) like the reference (result key sharded like the join
    key, dataflow.rs:2371-2379); outer 'ears' appear when a side has no
    match. For every affected join-key group the output set is recomputed
    before/after and differenced — correct under arbitrary retraction.
    """

    arity = 2
    # pure memo (lk, rk) -> mixed output pointer: every entry recomputes
    # to the same value via mix_pointers, so the coverage sanitizer must
    # not demand its capture (snapshot_state deliberately skips it)
    _snapshot_sanitizer_exempt = ("_mix_cache",)

    def __init__(self, mode: str, lkey_fn, rkey_fn,
                 out_fn: Callable[[Pointer | None, tuple | None, Pointer | None, tuple | None], tuple],
                 out_key_fn=None, left_id_only: bool = False,
                 out_spec: tuple | None = None,
                 lkey_pos: int | None = None, lkey_fb=None,
                 rkey_pos: int | None = None, rkey_fb=None):
        assert mode in ("inner", "left", "right", "outer")
        self.mode = mode
        self.lkey_fn = lkey_fn
        self.rkey_fn = rkey_fn
        self.out_fn = out_fn
        # C-friendly projection spec ((side, pos), ...) mirroring out_fn;
        # side 0 = left row, 1 = right row, 2 = key (pos 0 lk / 1 rk)
        self.out_spec = out_spec
        # plain-column join keys: the native pass extracts row[pos] inline
        # (fb(v, key) reproduces the lowering's _jkey for non-str/int cells)
        self.lkey_pos = lkey_pos
        self.lkey_fb = lkey_fb
        self.rkey_pos = rkey_pos
        self.rkey_fb = rkey_fb
        # default out key = mix(left id, right id): unique per pair, so the
        # bilinear delta path applies. A custom out_key_fn (join id from one
        # side) can collide across pairs — those joins keep the per-group
        # recompute path whose dict semantics dedupe collisions.
        self._bilinear = out_key_fn is None
        # only the inner bilinear fast path fuses same-tick retract+insert
        # pairs; other modes would forward an uncanceled net-zero pair to
        # sinks as phantom delete+insert events, so they keep consolidation
        self.consolidate_inputs = not (self._bilinear and mode == "inner")
        # live (lk, rk) pairs recur every tick in dimension joins: a dict
        # probe beats re-mixing 128-bit ints per emitted row
        self._mix_cache: dict = {}
        self.out_key_fn = out_key_fn or self._default_out_key
        self.left: dict[Any, dict[Pointer, tuple]] = {}
        self.right: dict[Any, dict[Pointer, tuple]] = {}
        self.left_id_only = left_id_only

    def exchange_specs(self):
        # both sides route by join key so each key group is wholly owned by
        # one worker (reference: join exchanges, dataflow.rs:2276)
        return [lambda k, r: self.lkey_fn(k, r),
                lambda k, r: self.rkey_fn(k, r)]

    def snapshot_state(self):
        # _mix_cache is a pure memo (rebuilds on demand) — never captured
        return {"left": self.left, "right": self.right}

    def restore_state(self, state) -> None:
        self.left = {jk: dict(g) for jk, g in state["left"].items()}
        self.right = {jk: dict(g) for jk, g in state["right"].items()}

    def _default_out_key(self, lkey, rkey, jk):
        ck = (lkey, rkey)
        p = self._mix_cache.get(ck)
        if p is None:
            p = mix_pointers(lkey, rkey)
            if len(self._mix_cache) < (1 << 20):
                self._mix_cache[ck] = p
        return p

    def _group_out(self, jk) -> dict[Pointer, tuple]:
        lg = self.left.get(jk) or {}
        rg = self.right.get(jk) or {}
        out: dict[Pointer, tuple] = {}
        if lg and rg:
            for lk, lrow in lg.items():
                for rk, rrow in rg.items():
                    out[self.out_key_fn(lk, rk, jk)] = self.out_fn(lk, lrow, rk, rrow)
        if self.mode in ("left", "outer") and lg and not rg:
            for lk, lrow in lg.items():
                out[self.out_key_fn(lk, None, jk)] = self.out_fn(lk, lrow, None, None)
        if self.mode in ("right", "outer") and rg and not lg:
            for rk, rrow in rg.items():
                out[self.out_key_fn(None, rk, jk)] = self.out_fn(None, None, rk, rrow)
        return out

    @staticmethod
    def _apply(index, jk, key, row, diff):
        grp = index.setdefault(jk, {})
        if diff > 0:
            grp[key] = row
        else:
            grp.pop(key, None)
            if not grp:
                index.pop(jk, None)

    def step(self, time, in_deltas):
        dl, dr = in_deltas
        if not dl and not dr:
            return Delta()
        if self._bilinear and self.mode == "inner":
            fj = _get_fastjoin()
            if fj is not None:
                return self._step_inner_native(fj, dl, dr)
        l_entries = [(self.lkey_fn(k, r), k, r, d) for k, r, d in dl.entries]
        r_entries = [(self.rkey_fn(k, r), k, r, d) for k, r, d in dr.entries]
        if self._bilinear:
            return self._step_bilinear(l_entries, r_entries)
        affected: dict[Any, None] = {}
        for jk, _, _, _ in l_entries:
            affected[jk] = None
        for jk, _, _, _ in r_entries:
            affected[jk] = None
        affected.pop(None, None)  # null join keys never match
        old = {jk: self._group_out(jk) for jk in affected}
        for jk, k, r, d in l_entries:
            if jk is not None:
                self._apply(self.left, jk, k, r, d)
        for jk, k, r, d in r_entries:
            if jk is not None:
                self._apply(self.right, jk, k, r, d)
        out = Delta()
        for jk in affected:
            new = self._group_out(jk)
            o = old[jk]
            for okey, orow in o.items():
                n = new.get(okey)
                if n is None or row_fingerprint(n) != row_fingerprint(orow):
                    out.append(okey, orow, -1)
            for okey, nrow in new.items():
                oo = o.get(okey)
                if oo is None or row_fingerprint(oo) != row_fingerprint(nrow):
                    out.append(okey, nrow, 1)
        return out.consolidate()

    def _emit_left(self, out, jk, lk, lrow, sign) -> None:
        """Output delta for one left row vs the CURRENT right state."""
        rg = self.right.get(jk)
        if rg:
            append = out.entries.append
            okey, ofn = self.out_key_fn, self.out_fn
            for rk, rrow in rg.items():
                append((okey(lk, rk, jk), ofn(lk, lrow, rk, rrow), sign))
        elif self.mode in ("left", "outer"):
            out.append(self.out_key_fn(lk, None, jk),
                       self.out_fn(lk, lrow, None, None), sign)

    def _emit_right(self, out, jk, rk, rrow, sign) -> None:
        lg = self.left.get(jk)
        if lg:
            append = out.entries.append
            okey, ofn = self.out_key_fn, self.out_fn
            for lk, lrow in lg.items():
                append((okey(lk, rk, jk), ofn(lk, lrow, rk, rrow), sign))
        elif self.mode in ("right", "outer"):
            out.append(self.out_key_fn(None, rk, jk),
                       self.out_fn(None, None, rk, rrow), sign)

    def _step_bilinear(self, l_entries, r_entries) -> Delta:
        """Exact incremental join delta: ΔL⋈R_old + L_new⋈ΔR (+ ear
        emptiness transitions for left/right/outer) — O(delta x matches)
        instead of recomputing every affected group (the DD join_core
        update rule the reference leans on, dataflow.rs:2276).

        State applies ENTRY BY ENTRY while the side's delta is processed,
        matching the recompute path's dict semantics exactly: an insert
        over a live row is an upsert (old outputs retracted first, no-op
        if the row is unchanged) and a retraction of an absent row emits
        nothing. Right state stays fixed during the ΔL pass (R_old) and
        left state is complete during the ΔR pass (L_new) — the bilinear
        split that makes the delta exact."""
        if self.mode == "inner":
            return self._step_bilinear_inner(l_entries, r_entries)
        out = Delta()
        left_ear = self.mode in ("left", "outer")
        right_ear = self.mode in ("right", "outer")
        fp = row_fingerprint
        # left-group emptiness transitions flip right-side ears; snapshot
        # before ΔL applies
        if right_ear:
            l_empty_old: dict[Any, bool] = {}
            for jk, _, _, _ in l_entries:
                if jk is not None and jk not in l_empty_old:
                    l_empty_old[jk] = jk not in self.left
        # ΔL against R_old, left state applied as we go
        for jk, lk, lrow, d in l_entries:
            if jk is None:
                continue
            lg = self.left.get(jk)
            cur = lg.get(lk) if lg else None
            if d > 0:
                if cur is not None:
                    if fp(cur) == fp(lrow):
                        continue  # duplicate upsert: outputs unchanged
                    self._emit_left(out, jk, lk, cur, -1)
                self._emit_left(out, jk, lk, lrow, 1)
                self._apply(self.left, jk, lk, lrow, 1)
            else:
                if cur is None:
                    continue  # retraction of an absent row: no-op
                self._emit_left(out, jk, lk, cur, -1)
                self._apply(self.left, jk, lk, lrow, -1)
        if right_ear:
            for jk, was_empty in l_empty_old.items():
                if (jk not in self.left) != was_empty:
                    rg = self.right.get(jk)
                    if rg:
                        sign = -1 if was_empty else 1
                        okey, ofn = self.out_key_fn, self.out_fn
                        for rk, rrow in rg.items():
                            out.append(okey(None, rk, jk),
                                       ofn(None, None, rk, rrow), sign)
        # ΔR against L_new, right state applied as we go
        if left_ear:
            r_empty_old: dict[Any, bool] = {}
            for jk, _, _, _ in r_entries:
                if jk is not None and jk not in r_empty_old:
                    r_empty_old[jk] = jk not in self.right
        for jk, rk, rrow, d in r_entries:
            if jk is None:
                continue
            rg = self.right.get(jk)
            cur = rg.get(rk) if rg else None
            if d > 0:
                if cur is not None:
                    if fp(cur) == fp(rrow):
                        continue
                    self._emit_right(out, jk, rk, cur, -1)
                self._emit_right(out, jk, rk, rrow, 1)
                self._apply(self.right, jk, rk, rrow, 1)
            else:
                if cur is None:
                    continue
                self._emit_right(out, jk, rk, cur, -1)
                self._apply(self.right, jk, rk, rrow, -1)
        # right-group emptiness transitions flip left-side ears (vs L_new)
        if left_ear:
            for jk, was_empty in r_empty_old.items():
                if (jk not in self.right) != was_empty:
                    lg = self.left.get(jk)
                    if lg:
                        sign = -1 if was_empty else 1
                        okey, ofn = self.out_key_fn, self.out_fn
                        for lk, lrow in lg.items():
                            out.append(okey(lk, None, jk),
                                       ofn(lk, lrow, None, None), sign)
        # NOT consolidated: emissions are exact multiset deltas already
        # (upserts skip unchanged rows; out keys are unique per pair), and
        # fingerprinting a dimension join's whole churn every tick was the
        # single largest cost in bench_etl. Exchange merges and captures
        # consolidate where it matters.
        return out

    def _one_side_inner(self, entries, my_index, other_index, flip):
        """One bilinear pass of the inner-mode fast path. Adjacent
        retract+insert of the same (jk, row-key) — the exact shape a
        groupby's churn arrives in — fuse into one upsert: one state scan
        and one output key per matched pair instead of two."""
        out_entries: list = []
        append = out_entries.append
        eq = _rows_equal
        okey, ofn = self.out_key_fn, self.out_fn
        i, n = 0, len(entries)
        while i < n:
            jk, k, row, d = entries[i]
            i += 1
            if jk is None:
                continue
            grp = my_index.get(jk)
            cur = grp.get(k) if grp else None
            if d > 0:
                if cur is not None:
                    if eq(cur, row):
                        continue  # duplicate upsert: outputs unchanged
                    og = other_index.get(jk)
                    if og:
                        for ok_, orow in og.items():
                            if flip:
                                key = okey(ok_, k, jk)
                                append((key, ofn(ok_, orow, k, cur), -1))
                                append((key, ofn(ok_, orow, k, row), 1))
                            else:
                                key = okey(k, ok_, jk)
                                append((key, ofn(k, cur, ok_, orow), -1))
                                append((key, ofn(k, row, ok_, orow), 1))
                    grp[k] = row
                else:
                    og = other_index.get(jk)
                    if og:
                        if flip:
                            for ok_, orow in og.items():
                                append((okey(ok_, k, jk),
                                        ofn(ok_, orow, k, row), 1))
                        else:
                            for ok_, orow in og.items():
                                append((okey(k, ok_, jk),
                                        ofn(k, row, ok_, orow), 1))
                    self._apply(my_index, jk, k, row, 1)
            else:
                if cur is None:
                    continue  # retraction of an absent row: no-op
                nxt = None
                if i < n:
                    jk2, k2, row2, d2 = entries[i]
                    if d2 > 0 and k2 == k and jk2 == jk:
                        nxt = row2
                        i += 1
                if nxt is not None:
                    if eq(cur, nxt):
                        continue  # value unchanged: no outputs, no state
                    og = other_index.get(jk)
                    if og:
                        for ok_, orow in og.items():
                            if flip:
                                key = okey(ok_, k, jk)
                                append((key, ofn(ok_, orow, k, cur), -1))
                                append((key, ofn(ok_, orow, k, nxt), 1))
                            else:
                                key = okey(k, ok_, jk)
                                append((key, ofn(k, cur, ok_, orow), -1))
                                append((key, ofn(k, nxt, ok_, orow), 1))
                    grp[k] = nxt
                else:
                    og = other_index.get(jk)
                    if og:
                        if flip:
                            for ok_, orow in og.items():
                                append((okey(ok_, k, jk),
                                        ofn(ok_, orow, k, cur), -1))
                        else:
                            for ok_, orow in og.items():
                                append((okey(k, ok_, jk),
                                        ofn(k, cur, ok_, orow), -1))
                    self._apply(my_index, jk, k, row, -1)
        return out_entries

    def _step_inner_native(self, fj, dl: Delta, dr: Delta) -> Delta:
        """Inner bilinear delta via the native pass (native/fastjoin.cpp).
        Raw delta entries go straight in when the join key is a plain
        column (lkey_pos); otherwise the pre-keyed 4-tuple list is built
        here and the C side skips extraction."""
        spec = self.out_spec
        ofn = self.out_fn if spec is None else None
        out = Delta()
        ext = out.entries.extend
        if dl.entries:
            if self.lkey_pos is not None:
                ext(fj.one_side_inner(
                    dl.entries, self.left, self.right, self._mix_cache,
                    mix_pointers, Pointer, ofn, spec, False,
                    self.lkey_pos, self.lkey_fb))
            else:
                les = [(self.lkey_fn(k, r), k, r, d)
                       for k, r, d in dl.entries]
                ext(fj.one_side_inner(
                    les, self.left, self.right, self._mix_cache,
                    mix_pointers, Pointer, ofn, spec, False, -1, None))
        if dr.entries:
            if self.rkey_pos is not None:
                ext(fj.one_side_inner(
                    dr.entries, self.right, self.left, self._mix_cache,
                    mix_pointers, Pointer, ofn, spec, True,
                    self.rkey_pos, self.rkey_fb))
            else:
                res = [(self.rkey_fn(k, r), k, r, d)
                       for k, r, d in dr.entries]
                ext(fj.one_side_inner(
                    res, self.right, self.left, self._mix_cache,
                    mix_pointers, Pointer, ofn, spec, True, -1, None))
        return out

    def _step_bilinear_inner(self, l_entries, r_entries) -> Delta:
        """Inner-mode bilinear delta: same exact-update rule as the generic
        path (ΔL vs R_old, then ΔR vs L_new) without ear bookkeeping, with
        upsert-pair fusion (see _one_side_inner). Pure-Python fallback for
        environments without the native pass."""
        out = Delta()
        if l_entries:
            out.entries.extend(
                self._one_side_inner(l_entries, self.left, self.right,
                                     flip=False))
        if r_entries:
            out.entries.extend(
                self._one_side_inner(r_entries, self.right, self.left,
                                     flip=True))
        return out


class DeduplicateOperator(Operator):
    """pw.Table.deduplicate (reference: deduplicate, dataflow.rs:3013):
    per instance keep one accepted value; ``acceptor(new, old) -> bool``
    decides replacement. Append-only w.r.t. input deletions (ignored)."""

    def __init__(self, instance_fn, value_fn, acceptor, full_row: bool = True):
        self.instance_fn = instance_fn
        self.value_fn = value_fn
        self.acceptor = acceptor
        self.state: dict[Any, tuple[Pointer, tuple]] = {}

    def snapshot_state(self):
        return {"state": self.state}

    def restore_state(self, state) -> None:
        self.state = {inst: (k, tuple(r))
                      for inst, (k, r) in state["state"].items()}

    def exchange_specs(self):
        # per-instance acceptance is order-sensitive: a single worker must
        # own each instance (reference: deduplicate exchanges by instance)
        return [lambda k, r: self.instance_fn(k, r)]

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta()
        # canonical per-tick order: acceptance is order-sensitive, and the
        # sharded exchange permutes same-tick arrival order — sorting by key
        # keeps results identical at any worker count (across ticks the
        # stream order still governs, as before)
        for key, row, diff in sorted(
                delta.entries, key=lambda e: int(e[0])):
            if diff <= 0:
                continue  # deduplicate consumes append-only streams
            inst = self.instance_fn(key, row)
            new_val = self.value_fn(key, row)
            cur = self.state.get(inst)
            if cur is None:
                accept = True
            else:
                old_val = self.value_fn(cur[0], cur[1])
                try:
                    accept = bool(self.acceptor(new_val, old_val))
                except Exception as e:
                    from pathway_tpu.internals.error import global_error_log

                    global_error_log().log(
                        f"deduplicate acceptor raised: {e!r}", "deduplicate")
                    accept = False
            if accept:
                gkey = hash_values("dedup", inst)
                if cur is not None:
                    out.append(gkey, cur[1], -1)
                self.state[inst] = (key, row)
                out.append(gkey, row, 1)
        return out.consolidate()


class OutputOperator(Operator):
    """Terminal capture: invokes callback(time, delta); passes delta through.

    Under operator-state snapshots (engine/persistence.py) it additionally
    tracks the CONSOLIDATED emitted state — key -> (row, net count) — so a
    restart restored from a snapshot can re-emit the covered prefix's
    visible state to fresh sinks, exactly as a full-WAL replay would have
    re-emitted it by reprocessing the prefix. Tracking is off (zero cost)
    unless the runtime enables it for a snapshotting run.
    """

    def __init__(self, callback: Callable[[int, Delta], None]):
        self.callback = callback
        self.track_emitted = False
        self.emitted: dict[Pointer, list] = {}  # key -> [row, net count]

    def replicate(self, n):
        # all workers funnel into the same sink: share the callback object
        # (a deepcopy of a bound method would clone its receiver and the
        # replica outputs would silently vanish into the copy)
        reps = [self]
        for _ in range(n - 1):
            r = OutputOperator(self.callback)
            r.track_emitted = self.track_emitted
            reps.append(r)
        return reps

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if delta:
            if self.track_emitted:
                self._track(delta)
            self.callback(time, delta)
        return delta

    def _track(self, delta: Delta) -> None:
        emitted = self.emitted
        for key, row, diff in delta.entries:
            cur = emitted.get(key)
            c = (cur[1] if cur is not None else 0) + diff
            if c <= 0:
                emitted.pop(key, None)
            elif diff > 0 or cur is None:
                emitted[key] = [row, c]
            else:
                cur[1] = c

    def snapshot_state(self):
        if not self.track_emitted:
            return None
        return {"emitted": {k: (tuple(r), c)
                            for k, (r, c) in self.emitted.items()}}

    def restore_state(self, state) -> None:
        self.track_emitted = True
        self.emitted = {k: [tuple(r), c]
                        for k, (r, c) in state["emitted"].items()}

    def emit_restored(self, time: int) -> None:
        """Push the restored consolidated state to the sink as one initial
        delta — the snapshot-mode stand-in for the output rows a full
        replay of the covered prefix would have re-emitted."""
        if self.emitted:
            self.callback(time, Delta(
                [(k, r, c) for k, (r, c) in self.emitted.items()]))

    def notify_time_end(self, time):
        pass


class StatefulArrangeOperator(Operator):
    """Materializes its input (identity + arrangement), for ix/debug reads."""

    def __init__(self):
        self.state = Arrangement()

    def exchange_specs(self):
        return [Exchange.BY_KEY]

    def snapshot_state(self):
        return {"rows": self.state.rows}

    def restore_state(self, state) -> None:
        self.state.rows = dict(state["rows"])

    def step(self, time, in_deltas):
        self.state.update(in_deltas[0])
        return in_deltas[0]


class SortOperator(Operator):
    """prev/next pointers within (instance, sort-key) order
    (reference: sort_table, dataflow.rs:1910; operators/prev_next.rs).

    Round-1 implementation recomputes neighbours for the affected instance
    on change — O(n log n) per touched instance, correct under retraction.
    """

    def __init__(self, key_fn, instance_fn):
        self.key_fn = key_fn
        self.instance_fn = instance_fn
        self.instances: dict[Any, dict[Pointer, Any]] = {}
        self.out = Arrangement()

    def exchange_specs(self):
        # prev/next neighbours are computed within an instance: one worker
        # must own each instance (reference: operators/prev_next.rs)
        return [lambda k, r: self.instance_fn(k, r)]

    def snapshot_state(self):
        return {"instances": self.instances, "out": self.out.rows}

    def restore_state(self, state) -> None:
        self.instances = {inst: dict(g)
                          for inst, g in state["instances"].items()}
        self.out.rows = dict(state["out"])

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return Delta()
        touched: dict[Any, None] = {}
        removed: list[Pointer] = []
        for key, row, diff in delta.entries:
            inst = self.instance_fn(key, row)
            grp = self.instances.setdefault(inst, {})
            if diff > 0:
                grp[key] = self.key_fn(key, row)
            else:
                if key in grp:
                    grp.pop(key)
                    removed.append(key)
            touched[inst] = None
        out = Delta()
        for key in removed:
            # only retract if the key wasn't re-inserted (possibly under
            # another instance) in this same delta
            if not any(key in g for g in self.instances.values()):
                upsert_delta(self.out, key, None, out)
        for inst in touched:
            grp = self.instances.get(inst, {})
            order = sorted(grp.items(), key=lambda kv: (_sortable(kv[1]), int(kv[0])))
            for i, (key, _sk) in enumerate(order):
                prev_k = order[i - 1][0] if i > 0 else None
                next_k = order[i + 1][0] if i + 1 < len(order) else None
                upsert_delta(self.out, key, (prev_k, next_k), out)
        self.out.update(out)
        return out


def _sortable(v):
    if v is None:
        return (0, 0)
    if isinstance(v, (bool, int, float, np.integer, np.floating)):
        return (1, float(v))
    if isinstance(v, str):
        return (2, v)
    return (3, repr(v))


class GradualBroadcastOperator(Operator):
    """Throttled broadcast of a changing (lower, value, upper) triplet
    (reference: src/engine/dataflow/operators/gradual_broadcast.rs:1-490).

    Every target row gets an ``apx_value`` column approximating the
    broadcast value: keys below ``threshold = (value-lower)/(upper-lower)
    x KEY_MAX`` see ``upper``, the rest see ``lower``. When the value
    moves, only keys BETWEEN the old and new thresholds change — so a
    jittering broadcast scalar retracts O(moved fraction) of rows instead
    of all of them (apply_to_fragment from..to, gradual_broadcast.rs:
    421-460). Input 0: target rows; input 1: the triplet table (last
    insert wins, like the reference's broadcast stream).
    """

    arity = 2
    _KEY_SPACE = 1 << 128
    _MISSING = object()  # 'never emitted' sentinel (None is a legal apx)

    def __init__(self):
        self.rows: dict[Pointer, tuple] = {}
        self._sorted_keys: list[int] = []  # int(key), ascending
        self._by_int: dict[int, Pointer] = {}
        self.triplet: tuple | None = None
        self._threshold: int | None = None  # threshold of last emission
        self.emitted_apx: dict[Pointer, Any] = {}

    def snapshot_state(self):
        # emitted_apx may hold the _MISSING sentinel only transiently
        # (pop side) — live values are plain data
        return {"rows": self.rows, "triplet": self.triplet,
                "threshold": self._threshold,
                "emitted_apx": self.emitted_apx}

    def restore_state(self, state) -> None:
        self.rows = dict(state["rows"])
        self.triplet = state["triplet"]
        self._threshold = state["threshold"]
        self.emitted_apx = dict(state["emitted_apx"])
        self._sorted_keys = sorted(int(k) for k in self.rows)
        self._by_int = {int(k): k for k in self.rows}

    def exchange_specs(self):
        # rows shard by key; the triplet stream is broadcast so every
        # shard applies the same thresholds (reference: the broadcast
        # stream in gradual_broadcast.rs) — per-key apx values are
        # independent, so sharding is exact
        return [Exchange.BY_KEY, Exchange.BROADCAST]

    def _threshold_of(self, triplet) -> int:
        lower, value, upper = triplet
        try:
            span = upper - lower
            frac = 1.0 if span == 0 else (value - lower) / span
        except TypeError:
            frac = 1.0
        frac = min(1.0, max(0.0, float(frac)))
        return int(frac * self._KEY_SPACE)

    def _apx_of(self, key: Pointer) -> Any:
        lower, _value, upper = self.triplet
        return upper if int(key) < self._threshold else lower

    def _emit_upsert(self, out: Delta, key: Pointer, row: tuple) -> None:
        apx = self._apx_of(key)
        old = self.emitted_apx.get(key, self._MISSING)
        if old is self._MISSING:
            out.append(key, (*row, apx), 1)
            self.emitted_apx[key] = apx
        elif row_fingerprint((old,)) != row_fingerprint((apx,)):
            out.append(key, (*row, old), -1)
            out.append(key, (*row, apx), 1)
            self.emitted_apx[key] = apx

    def step(self, time, in_deltas):
        import bisect

        d_rows, d_thr = in_deltas
        out = Delta()
        old_triplet = self.triplet
        if d_thr:
            # canonical order: the broadcast merges parts in arbitrary
            # order; "last insert wins" must not depend on worker count
            for _k, row, diff in sorted(
                    d_thr.entries,
                    key=lambda e: (int(e[0]), e[2], row_fingerprint(e[1]))):
                if diff > 0:
                    self.triplet = (row[0], row[1], row[2])
        if d_rows:
            # canonical order: retractions before insertions per key (same
            # hazard GroupByOperator sorts for, operators.py:332 — an
            # update pair may arrive insert-first after exchange merging)
            for key, row, diff in sorted(
                    d_rows.entries,
                    key=lambda e: (int(e[0]), e[2], row_fingerprint(e[1]))):
                ik = int(key)
                if diff > 0:
                    if key not in self.rows:
                        bisect.insort(self._sorted_keys, ik)
                        self._by_int[ik] = key
                    self.rows[key] = row
                    if self.triplet is not None:
                        if self._threshold is None:
                            self._threshold = self._threshold_of(
                                self.triplet)
                        apx = self._apx_of(key)
                        out.append(key, (*row, apx), 1)
                        self.emitted_apx[key] = apx
                else:
                    if key in self.rows:
                        idx = bisect.bisect_left(self._sorted_keys, ik)
                        if (idx < len(self._sorted_keys)
                                and self._sorted_keys[idx] == ik):
                            self._sorted_keys.pop(idx)
                        self._by_int.pop(ik, None)
                    self.rows.pop(key, None)
                    old = self.emitted_apx.pop(key, self._MISSING)
                    if old is not self._MISSING:
                        out.append(key, (*row, old), -1)
        if d_thr and self.triplet is not None:
            new_thr = self._threshold_of(self.triplet)
            bounds_changed = (
                old_triplet is None
                or old_triplet[0] != self.triplet[0]
                or old_triplet[2] != self.triplet[2])
            old_thr = self._threshold
            self._threshold = new_thr
            if bounds_changed or old_thr is None:
                # lower/upper changed: every emitted apx may be stale
                for key, row in self.rows.items():
                    self._emit_upsert(out, key, row)
            elif new_thr != old_thr:
                # only the key band between the thresholds flips
                # (reference apply_to_fragment from..to,
                # gradual_broadcast.rs:421-460)
                lo, hi = min(old_thr, new_thr), max(old_thr, new_thr)
                i = bisect.bisect_left(self._sorted_keys, lo)
                j = bisect.bisect_left(self._sorted_keys, hi)
                for ik in self._sorted_keys[i:j]:
                    key = self._by_int[ik]
                    self._emit_upsert(out, key, self.rows[key])
        return out.consolidate()
