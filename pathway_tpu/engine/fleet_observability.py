"""Fleet-wide observability plane: cross-process request tracing,
aggregated metrics, and the perf-trajectory regression watch.

PRs 11–13 turned one process into a fleet (SPMD workers,
snapshot-hydrated replicas, a latency-aware router), but every
observability surface so far was strictly per-process: a query that
entered the router and failed over across two replicas produced three
disjoint traces under three unrelated request ids, and there was no
single scrape point for the fleet. This module is the glue that makes
the fleet observable AS a fleet:

**Request-id propagation.** One id names a query end to end. The
webserver (io/http) adopts an inbound ``X-Pathway-Request-Id`` instead
of minting a fresh one; the router forwards the id (plus an
``X-Pathway-Hop`` counter) on every proxied attempt *including failover
replays*, and echoes it on every response *including 503s*. The
router's own per-request record carries the :data:`ROUTER_STAGES`
(``route``/``forward``/``failover``) — the fleet-side prefix of the
PR-6 per-process stage decomposition.

**Clock-aligned trace merge.** Each process's flight recorder stamps
its Chrome-trace payload with ``pathway_meta`` — os pid, role
(primary/replica/router), process label, and a monotonic↔wall clock
anchor (``epoch_wall_us``: the wall-clock microsecond that perf-counter
zero of the trace timeline maps to). The same anchor rides the PR-12
control-channel heartbeats, so the router can align endpoints it never
scraped a file from. :func:`merge_traces` shifts every process's events
onto ONE wall-clock timeline, renames process tracks, and draws
cross-process flow arrows between the router's request span and the
serving process's request span that share a request id — a failover
renders as an arrow from the router into the RESCUING replica's track.
Consumers: ``python -m pathway_tpu trace-merge <dir>`` (offline, over
written trace files) and the router's ``/fleet/trace`` (live, over each
endpoint's ``/trace?format=chrome``).

**Metrics aggregation.** :func:`merge_metrics` takes each process's
Prometheus exposition text and emits ONE fleet document: every family
declared with exactly one ``# TYPE`` line (N processes shipping the
same family must not redeclare it), every sample re-labeled with
``process=``/``role=``, and — where merging is mathematically sound —
an extra ``process="_fleet"`` aggregate: counters sum, histograms sum
bucket-wise (cumulative buckets stay monotone under addition). Gauges
and quantile summaries pass through per-process only: averaging P²
quantiles is not a quantile of the union, so no fake fleet p50 is
invented. Served by the router as ``/fleet/metrics``.

**Perf-trajectory watch.** Every bench leg appends rows to
``BENCH_HISTORY.jsonl`` (one JSON object per line: leg, metric, value,
git sha, timestamp) and ``bench.py --check-regression`` compares each
series' newest point against the trailing median of its prior points
with per-metric tolerance bands — the ROADMAP's evidence rule gets a
*trajectory*, not just a last-good snapshot.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import statistics
import time

logger = logging.getLogger(__name__)

# the cross-process propagation headers (README "Observability > Fleet")
REQUEST_ID_HEADER = "X-Pathway-Request-Id"
HOP_HEADER = "X-Pathway-Hop"

# router-side request stages — the fleet prefix of the per-process
# STAGES, defined next to them in engine/request_tracker.py: `route`
# (endpoint choice), `forward` (the first proxy attempt), `failover`
# (each replay on the next-best replica after a connection failure)
from pathway_tpu.engine.request_tracker import ROUTER_STAGES  # noqa: E402

_HISTORY_DEFAULT = "BENCH_HISTORY.jsonl"


def clock_anchor() -> dict:
    """A monotonic↔wall mapping taken NOW: ``wall - perf`` is the
    wall-clock second that perf-counter zero maps to in this process.
    Shipped in heartbeats so the router can align an endpoint's
    monotonic trace timestamps without scraping its trace payload."""
    return {"perf": time.perf_counter(), "wall": time.time()}


def anchor_epoch_wall_us(anchor: dict, epoch_perf: float) -> float:
    """Wall-clock microseconds of a perf-counter ``epoch_perf`` under
    ``anchor`` (a :func:`clock_anchor` dict)."""
    return (anchor["wall"] - anchor["perf"] + epoch_perf) * 1e6


# ---------------------------------------------------------------------------
# router-side request spans
# ---------------------------------------------------------------------------

class RouterSpan:
    """One query's router-side record: the request id it carried (or was
    assigned), per-stage perf_counter stamps, and the per-attempt
    forward/failover outcomes. The router mutates it inline during
    ``forward()``; ``RouterRequestLog.finish`` freezes it into the
    bounded completed ring."""

    __slots__ = ("rid", "path", "t0", "t_routed", "attempts", "status",
                 "replica", "t_done")

    def __init__(self, rid: str, path: str, t0: float):
        self.rid = rid
        self.path = path
        self.t0 = t0
        self.t_routed: float | None = None
        # (stage, replica_id, t_start, t_end, ok) — stage is "forward"
        # for the first attempt, "failover" for each replay
        self.attempts: list[tuple] = []
        self.status: int | None = None
        self.replica: str | None = None
        self.t_done: float | None = None

    def note_routed(self) -> None:
        if self.t_routed is None:
            self.t_routed = time.perf_counter()

    def note_attempt(self, replica_id: str, t_start: float,
                     ok: bool) -> None:
        stage = "forward" if not self.attempts else "failover"
        self.attempts.append(
            (stage, replica_id, t_start, time.perf_counter(), ok))

    def failovers(self) -> int:
        return sum(1 for a in self.attempts if a[0] == "failover")


class RouterRequestLog:
    """Bounded ring of completed :class:`RouterSpan` records + streaming
    per-stage aggregates, and the Chrome-trace export that puts the
    router's view of each query on its own track (merged against the
    serving processes' request tracks by :func:`merge_traces`)."""

    def __init__(self, maxlen: int = 512):
        from pathway_tpu.engine.locking import create_lock
        from pathway_tpu.engine.request_tracker import P2Quantile

        self._lock = create_lock("RouterRequestLog._lock")
        self.completed: collections.deque = collections.deque(
            maxlen=max(8, maxlen))
        self._stage_p50 = {s: P2Quantile(0.5) for s in ROUTER_STAGES}
        self._stage_sum = {s: 0.0 for s in ROUTER_STAGES}
        self.epoch = time.perf_counter()
        self.epoch_wall_us = anchor_epoch_wall_us(clock_anchor(),
                                                  self.epoch)

    def start(self, rid: str, path: str) -> RouterSpan:
        return RouterSpan(rid, path, time.perf_counter())

    def finish(self, span: RouterSpan, status: int,
               replica: str | None) -> None:
        span.status = status
        span.replica = replica
        span.t_done = time.perf_counter()
        route_ms = ((span.t_routed or span.t0) - span.t0) * 1e3
        fwd_ms = sum((t1 - t0) * 1e3
                     for s, _r, t0, t1, _ok in span.attempts
                     if s == "forward")
        fo_ms = sum((t1 - t0) * 1e3
                    for s, _r, t0, t1, _ok in span.attempts
                    if s == "failover")
        with self._lock:
            for stage, ms in (("route", route_ms), ("forward", fwd_ms),
                              ("failover", fo_ms)):
                self._stage_sum[stage] += ms
                self._stage_p50[stage].observe(ms)
            self.completed.append(span)

    def stage_summary(self) -> dict:
        with self._lock:
            return {s: {"p50_ms": self._stage_p50[s].value(),
                        "sum_ms": round(self._stage_sum[s], 3)}
                    for s in ROUTER_STAGES}

    def chrome_trace_events(self) -> list[dict]:
        """The router's request track: one async (b/e) span per query
        named by its request id, with per-attempt child spans carrying
        the stage (forward/failover), replica and outcome. ``ts`` is
        relative to :attr:`epoch` — aligned fleet-wide via
        ``pathway_meta.epoch_wall_us``."""
        pid = os.getpid()
        with self._lock:
            spans = list(self.completed)
        if not spans:
            return []
        out = [{"ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
                "args": {"name": "router requests"}}]
        for span in spans:
            us = lambda t: (t - self.epoch) * 1e6  # noqa: E731
            fid = f"req-{span.rid}"
            name = f"req {span.rid}"
            args = {"request_id": span.rid, "path": span.path,
                    "status": span.status, "replica": span.replica,
                    "failovers": span.failovers()}
            t_end = span.t_done if span.t_done is not None else span.t0
            out.append({"ph": "b", "cat": "router_request", "id": fid,
                        "pid": pid, "tid": 0, "ts": us(span.t0),
                        "name": name, "args": args})
            for stage, replica, t0, t1, ok in span.attempts:
                out.append({"ph": "b", "cat": "router_request", "id": fid,
                            "pid": pid, "tid": 0, "ts": us(t0),
                            "name": f"{stage} {replica}",
                            "args": {"stage": stage, "replica": replica,
                                     "ok": ok}})
                out.append({"ph": "e", "cat": "router_request", "id": fid,
                            "pid": pid, "tid": 0, "ts": us(t1),
                            "name": f"{stage} {replica}"})
            out.append({"ph": "e", "cat": "router_request", "id": fid,
                        "pid": pid, "tid": 0, "ts": us(t_end),
                        "name": name})
        return out


# ---------------------------------------------------------------------------
# fleet trace merge
# ---------------------------------------------------------------------------

def merge_traces(payloads) -> dict:
    """Merge per-process Chrome-trace payloads into ONE clock-aligned
    timeline (module doc). Each payload is the dict written by
    ``FlightRecorder.write_chrome_trace`` / served by
    ``/trace?format=chrome`` — ``traceEvents`` plus a ``pathway_meta``
    block ``{pid, process, role, epoch_wall_us}``. Payloads without
    meta merge too (offset 0, anonymous process): a merged-but-
    misaligned trace beats no trace.

    Events keep their per-process relative order (B/E nesting is
    per-(pid, tid) and addition preserves order); every process is
    re-stamped with a unique merged pid and named via ``process_name``
    metadata; cross-process flow arrows (``s``/``t``/``f``) bind the
    router's request span to the serving process's request span that
    shares its request id."""
    payloads = [p for p in payloads
                if isinstance(p, dict) and isinstance(
                    p.get("traceEvents"), list)]
    if not payloads:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "pathway_fleet": {"processes": [],
                                  "cross_process_request_ids": []}}
    metas = []
    for i, p in enumerate(payloads):
        m = p.get("pathway_meta") or {}
        metas.append({
            "pid": int(m.get("pid", 0) or 0),
            "process": str(m.get("process") or f"proc{i}"),
            "role": str(m.get("role") or "unknown"),
            "epoch_wall_us": float(m.get("epoch_wall_us", 0.0) or 0.0),
        })
    # common origin: the earliest process epoch, so merged timestamps
    # start near zero instead of at "microseconds since 1970"
    anchored = [m["epoch_wall_us"] for m in metas if m["epoch_wall_us"]]
    origin_us = min(anchored) if anchored else 0.0

    events: list[dict] = []
    # request spans per merged pid: rid -> (begin ts, tid)
    serving_spans: dict[int, dict[str, tuple[float, int]]] = {}
    router_spans: dict[int, dict[str, dict]] = {}
    # promotion instants (mpid, ts, epoch) + each process's last event
    # ts — a failover is drawn as a flow arrow from the dead primary's
    # last recorded moment to the rescuer's promotion instant
    promotions: list[tuple[int, float, int]] = []
    last_ts: dict[int, float] = {}
    for mpid, (payload, meta) in enumerate(zip(payloads, metas)):
        shift_us = (meta["epoch_wall_us"] - origin_us) \
            if meta["epoch_wall_us"] else 0.0
        events.append({"ph": "M", "pid": mpid, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"{meta['role']}:"
                                        f"{meta['process']}"}})
        events.append({"ph": "M", "pid": mpid, "tid": 0,
                       "name": "process_sort_index",
                       "args": {"sort_index":
                                0 if meta["role"] == "router" else
                                1 if meta["role"] == "primary" else 2}})
        for ev in payload["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = mpid
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
                if ev.get("ph") != "M":
                    last_ts[mpid] = max(last_ts.get(mpid, 0.0), ev["ts"])
            events.append(ev)
            if ev.get("cat") == "promotion" and ev.get("ph") == "i":
                promotions.append(
                    (mpid, ev["ts"],
                     int((ev.get("args") or {}).get("epoch", 0))))
            rid = (ev.get("args") or {}).get("request_id")
            if rid and ev.get("ph") == "b":
                if ev.get("cat") == "router_request":
                    router_spans.setdefault(mpid, {}).setdefault(
                        rid, {"ts": ev["ts"], "tid": ev.get("tid", 0)})
                elif ev.get("cat") == "request":
                    serving_spans.setdefault(mpid, {}).setdefault(
                        rid, (ev["ts"], ev.get("tid", 2)))
    # cross-process flows: router request span -> every serving
    # process's span with the same id (normally exactly one — the
    # process that actually answered; after a failover that is the
    # RESCUING replica, so the arrow lands where the query did)
    cross_rids: set[str] = set()
    for rpid, by_rid in router_spans.items():
        for rid, src in by_rid.items():
            targets = [(spid, pos) for spid, spans in
                       serving_spans.items() for r, pos in spans.items()
                       if r == rid]
            if not targets:
                continue
            cross_rids.add(rid)
            fid = f"xreq-{rid}"
            events.append({"ph": "s", "cat": "fleet", "id": fid,
                           "pid": rpid, "tid": src["tid"],
                           "ts": src["ts"], "name": "request"})
            for k, (spid, (ts, tid)) in enumerate(sorted(targets)):
                ph = "f" if k == len(targets) - 1 else "t"
                ev = {"ph": ph, "cat": "fleet", "id": fid, "pid": spid,
                      "tid": tid, "ts": ts + 0.01, "name": "request"}
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
    # promotion handoff arrows: dead primary's last recorded moment ->
    # the rescuer's "promoted to primary" instant. The rescuer's meta
    # role already reads "primary" (it was promoted before the trace was
    # written), so the source is any OTHER primary-role process; with
    # none in the capture set (SIGKILL skips the trace-writing finally,
    # so the victim's trace exists only if it was scraped live), the
    # instant stands alone — still visible, just not bound.
    for ppid, p_ts, epoch in promotions:
        candidates = [i for i, m in enumerate(metas)
                      if i != ppid and m["role"] == "primary"
                      and i in last_ts]
        if not candidates:
            continue
        src = max(candidates, key=lambda i: last_ts[i])
        fid = f"promo-{epoch}-{ppid}"
        events.append({"ph": "s", "cat": "fleet", "id": fid, "pid": src,
                       "tid": 0, "ts": min(last_ts[src], p_ts),
                       "name": "promotion"})
        events.append({"ph": "f", "bp": "e", "cat": "fleet", "id": fid,
                       "pid": ppid, "tid": 0, "ts": p_ts + 0.01,
                       "name": "promotion"})
    # serving-only cross-process ids (e.g. primary handed off to a
    # replica without the router in the capture set) still count as
    # spanning processes
    by_rid_pids: dict[str, set[int]] = {}
    for pid, spans in serving_spans.items():
        for rid in spans:
            by_rid_pids.setdefault(rid, set()).add(pid)
    for pid, spans in router_spans.items():
        for rid in spans:
            by_rid_pids.setdefault(rid, set()).add(pid)
    cross_rids.update(r for r, pids in by_rid_pids.items()
                      if len(pids) > 1)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "pathway_fleet": {
            "processes": [{"pid": i, "process": m["process"],
                           "role": m["role"],
                           "epoch_wall_us": m["epoch_wall_us"]}
                          for i, m in enumerate(metas)],
            "cross_process_request_ids": sorted(cross_rids),
        },
    }


# ---------------------------------------------------------------------------
# fleet metrics merge
# ---------------------------------------------------------------------------

_TYPE_RE = re.compile(r"^# TYPE\s+(\S+)\s+(\S+)\s*$")
_SAMPLE_RE = re.compile(
    r'^(?P<family>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})?'
    r'\s+(?P<value>\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

# the fleet-aggregate pseudo-process label: counter/histogram sums
# across processes land under process="_fleet" (underscore-prefixed so
# it can never collide with a real replica id, which the router derives
# from PATHWAY_REPLICA_ID / pids)
FLEET_PROCESS = "_fleet"


def escape_label_value(v: str) -> str:
    """Prometheus exposition label-value escaping (the PR-5 contract)."""
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _parse_exposition(text: str):
    """Yield ("type", family, kind) and ("sample", family, labels_raw,
    value_str) items in document order; non-conforming lines are
    skipped (the per-process endpoints are already lint-gated)."""
    for line in text.splitlines():
        line = line.rstrip()
        if not line:
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                yield ("type", m.group(1), m.group(2))
            continue
        m = _SAMPLE_RE.match(line)
        if m:
            yield ("sample", m.group("family"),
                   m.group("labels") or "", m.group("value"))


def _base_family(family: str) -> str:
    return re.sub(r"_(bucket|sum|count)$", "", family)


def _float(v: str) -> float | None:
    if v == "+Inf":
        return float("inf")
    try:
        return float(v)
    except ValueError:
        return None


def merge_metrics(scrapes) -> str:
    """Merge per-process exposition documents into one fleet document.

    ``scrapes`` is an iterable of ``(meta, text)`` where ``meta`` is
    ``{"process": str, "role": str}`` and ``text`` one process's
    ``/metrics`` body. Contract (module doc + the exposition tests):

    * one ``# TYPE`` line per family, however many processes ship it
      (conflicting kinds keep the first and log — never redeclare);
    * every sample re-labeled ``process=``/``role=`` (label values
      escaped per the exposition format);
    * counters and histograms additionally aggregated under
      ``process="_fleet"``, summed per remaining label set (histogram
      cumulative buckets stay monotone under addition; the ``+Inf``
      bucket equals the summed ``_count``);
    * gauges and summaries pass through per-process only (averaging
      quantiles across processes is not a quantile of anything).
    """
    family_kind: dict[str, str] = {}
    family_order: list[str] = []
    # base family -> list of (sub_family, merged_labels_raw, value_str)
    samples: dict[str, list[tuple[str, str, str]]] = {}
    # (base family, sub family, non-process labels frozen) -> float sum,
    # for the _fleet aggregates
    sums: dict[tuple, float] = {}
    sum_order: list[tuple] = []

    for meta, text in scrapes:
        process = str(meta.get("process", "?"))
        role = str(meta.get("role", "unknown"))
        extra = (f'process="{escape_label_value(process)}",'
                 f'role="{escape_label_value(role)}"')
        for item in _parse_exposition(text):
            if item[0] == "type":
                _kind_tag, family, kind = item
                prior = family_kind.get(family)
                if prior is None:
                    family_kind[family] = kind
                    family_order.append(family)
                elif prior != kind:
                    logger.warning(
                        "fleet metrics: family %s arrives as %s from "
                        "%s but was first declared %s — keeping the "
                        "first declaration", family, kind, process,
                        prior)
                continue
            _tag, sub_family, labels_raw, value = item
            # group under the declared family: an exact declaration wins
            # (a counter literally NAMED foo_count must not be filed
            # under a phantom "foo"); only undeclared _bucket/_sum/
            # _count sub-samples resolve to their histogram/summary base
            base = sub_family if sub_family in family_kind \
                else _base_family(sub_family)
            merged = extra + ("," + labels_raw if labels_raw else "")
            samples.setdefault(base, []).append(
                (sub_family, merged, value))
            kind = family_kind.get(base)
            if kind in ("counter", "histogram"):
                v = _float(value)
                if v is not None:
                    key = (base, sub_family, labels_raw)
                    if key not in sums:
                        sum_order.append(key)
                        sums[key] = 0.0
                    sums[key] += v

    lines: list[str] = []
    fleet_extra = (f'process="{FLEET_PROCESS}",role="fleet"')
    agg_by_base: dict[str, list[tuple[str, str, float]]] = {}
    for base, sub_family, labels_raw in sum_order:
        agg_by_base.setdefault(base, []).append(
            (sub_family, labels_raw,
             sums[(base, sub_family, labels_raw)]))
    for family in family_order:
        if family not in samples and family not in agg_by_base:
            continue
        lines.append(f"# TYPE {family} {family_kind[family]}")
        for sub_family, labels_raw, value in samples.get(family, ()):
            lines.append(f"{sub_family}{{{labels_raw}}} {value}")
        for sub_family, labels_raw, total in agg_by_base.get(family, ()):
            merged = fleet_extra + ("," + labels_raw if labels_raw
                                    else "")
            out_v = format(total, "g") if total != int(total) \
                else str(int(total))
            lines.append(f"{sub_family}{{{merged}}} {out_v}")
    # families that arrived without a TYPE line still pass through,
    # per-process labeled, so nothing a process exported is dropped
    untyped = [f for f in samples if f not in family_kind]
    for family in untyped:
        for sub_family, labels_raw, value in samples[family]:
            lines.append(f"{sub_family}{{{labels_raw}}} {value}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# perf-trajectory watch (BENCH_HISTORY.jsonl)
# ---------------------------------------------------------------------------

def history_path(path: str | None = None) -> str:
    return path or os.environ.get("BENCH_HISTORY_PATH", _HISTORY_DEFAULT)


def git_sha() -> str | None:
    sha = os.environ.get("GITHUB_SHA")
    if sha:
        return sha[:12]
    import subprocess

    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short=12", "HEAD"],
            capture_output=True, text=True, timeout=5)
        if out.returncode == 0:
            return out.stdout.strip() or None
    except Exception:  # noqa: BLE001 — evidence, never a crash
        pass
    return None


def append_bench_history(leg: str, metrics: dict,
                         path: str | None = None,
                         sha: str | None = None,
                         at: float | None = None) -> int:
    """Append one row per numeric metric of one bench leg to the
    trajectory file (JSONL: ``{"leg","metric","value","sha","at"}``).
    Non-numeric values (and bools, and error strings) are skipped;
    returns the number of rows written. Append-only with line-granular
    records: a torn tail line is skipped by the reader, never fatal."""
    path = history_path(path)
    if sha is None:
        sha = git_sha()
    if at is None:
        at = time.time()
    rows = []
    for metric, value in sorted(metrics.items()):
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        rows.append(json.dumps({"leg": leg, "metric": metric,
                                "value": float(value), "sha": sha,
                                "at": at}))
    if not rows:
        return 0
    parent = os.path.dirname(os.path.abspath(path))
    if parent and not os.path.isdir(parent):
        os.makedirs(parent, exist_ok=True)
    from pathway_tpu.testing import faults

    # crash edge inside the append: a torn tail line is the reader's
    # skip-don't-die contract, and this point lets a test land there
    faults.hit("observability.history.append", path=str(path))
    with open(path, "a") as f:
        f.write("\n".join(rows) + "\n")
        f.flush()
        os.fsync(f.fileno())
    return len(rows)


def bench_history_rows(path: str | None = None) -> list[dict]:
    """All parseable trajectory rows, file order (= time order). A torn
    or foreign line is skipped, not fatal — the file is append-only
    evidence, and one bad write must not hide the rest."""
    path = history_path(path)
    rows: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(row, dict) and "metric" in row \
                        and isinstance(row.get("value"), (int, float)):
                    rows.append(row)
    except FileNotFoundError:
        pass
    return rows


# direction heuristics: which way is "worse" for a metric, by name.
# Higher-better markers win over the time-suffix check so
# "docs_per_s" / "rows_per_s" land on the right side of their own
# trailing "_s". Metrics matching neither are unwatched (reported, not
# gated) — an unknown metric must not produce a coin-flip gate.
_HIGHER_MARKERS = ("per_s", "per_sec", "docs_per", "rows_per",
                   "throughput", "efficiency", "vs_target", "vs_raw",
                   "overlap_ratio", "qps")
_LOWER_MARKERS = ("latency", "staleness", "lost", "amplification",
                  "stall", "lag", "compiles", "failures", "hang",
                  "skew")


def metric_direction(name: str) -> str | None:
    """'higher' (bigger is better), 'lower' (smaller is better), or
    None (unwatched)."""
    low = name.lower()
    if any(m in low for m in _HIGHER_MARKERS):
        return "higher"
    if any(m in low for m in _LOWER_MARKERS) \
            or low.endswith(("_ms", "_us", "_s")) \
            or re.search(r"_(ms|us|s)_\d+$", low):
        return "lower"
    return None


def check_regressions(path: str | None = None, *, window: int = 8,
                      min_prior: int = 3, tolerance: float | None = None,
                      tolerances: dict | None = None,
                      directions: dict | None = None) -> list[dict]:
    """Compare each (leg, metric) series' NEWEST point against the
    trailing median of up to ``window`` prior points. A series with
    fewer than ``min_prior`` prior points is young and passes (one CI
    run cannot regress against itself). Tolerance bands are relative:
    the default (``tolerance`` or ``BENCH_REGRESSION_TOLERANCE``,
    0.35 = 35%) can be overridden per metric via ``tolerances``
    (longest-prefix match on the metric name). Returns one record per
    flagged regression, worst first."""
    if tolerance is None:
        try:
            tolerance = float(os.environ.get(
                "BENCH_REGRESSION_TOLERANCE", 0.35))
        except ValueError:
            tolerance = 0.35
    series: dict[tuple[str, str], list[dict]] = {}
    for row in bench_history_rows(path):
        series.setdefault((str(row.get("leg", "?")), row["metric"]),
                          []).append(row)
    out: list[dict] = []
    for (leg, metric), rows in sorted(series.items()):
        direction = (directions or {}).get(metric) \
            or metric_direction(metric)
        if direction is None or len(rows) < min_prior + 1:
            continue
        newest = rows[-1]
        prior = [r["value"] for r in rows[max(0, len(rows) - 1 - window):
                                          len(rows) - 1]]
        med = statistics.median(prior)
        tol = tolerance
        if tolerances:
            best = -1
            for prefix, t in tolerances.items():
                if metric.startswith(prefix) and len(prefix) > best:
                    best, tol = len(prefix), t
        if med == 0:
            # a series pinned at zero (lost queries, demotions): any
            # nonzero newest point in the bad direction is a regression
            bad = newest["value"] > 0 if direction == "lower" \
                else newest["value"] < 0
            ratio = float("inf") if bad else 1.0
        else:
            ratio = newest["value"] / med
            bad = ratio > 1.0 + tol if direction == "lower" \
                else ratio < 1.0 - tol
        if bad:
            out.append({
                "leg": leg, "metric": metric,
                "value": newest["value"], "median": med,
                "ratio": (None if ratio == float("inf")
                          else round(ratio, 4)),
                "direction": direction, "tolerance": tol,
                "n_prior": len(prior), "sha": newest.get("sha"),
            })
    def severity(r):
        if r["ratio"] is None:
            return float("inf")
        return r["ratio"] if r["direction"] == "lower" \
            else 1.0 / max(r["ratio"], 1e-9)
    out.sort(key=severity, reverse=True)
    return out
