"""Multi-process dataflow execution: the host-level cluster plane.

Rebuild of the reference's timely TCP cluster
(src/engine/dataflow/config.rs:62-120 — ``PATHWAY_PROCESSES`` processes x
``PATHWAY_THREADS`` workers each, sockets at ``127.0.0.1:FIRST_PORT+i``;
CLI ``pathway spawn -n`` forks the same program per process). Every process
runs the IDENTICAL user program (SPMD), so all build the same engine graph
with the same node ids; global logical workers ``[0, P*T)`` are owned in
contiguous blocks of T per process, and rows cross processes only at
operator exchange boundaries.

Transport is ``multiprocessing.connection`` over loopback/LAN TCP — the
host-side control+exchange plane (the reference's timely ``communication``
crate). Device-side data parallelism rides the jax mesh/ICI instead
(parallel/mesh.py); this plane moves host rows and progress barriers, which
are control flow, not tensor math (SURVEY §5 distributed-communication
mapping).
"""

from __future__ import annotations

import os
import threading
import time
from multiprocessing.connection import Client, Connection, Listener
from typing import Any


class Cluster:
    """Pairwise duplex connections between the P processes of one run.

    Process ``i`` listens on ``first_port + i``; every ``j > i`` dials
    ``i``. All exchanges are bulk-synchronous: ``exchange(tag, msgs)``
    sends one message to every peer and returns one message from every
    peer, so each call is also a barrier (timely's progress channels
    collapse to this under whole-batch microbatch scheduling).
    """

    def __init__(self, n_processes: int, process_id: int, first_port: int,
                 run_id: str = ""):
        self.n_processes = int(n_processes)
        self.process_id = int(process_id)
        self.first_port = int(first_port)
        self.authkey = f"pathway-tpu/{run_id or 'cluster'}".encode()
        self.peers: dict[int, Connection] = {}
        self._listener: Listener | None = None
        self._seq = 0

    # -- wiring --------------------------------------------------------------
    def connect(self, timeout_s: float = 30.0) -> None:
        me = self.process_id
        host = os.environ.get("PATHWAY_CLUSTER_HOST", "127.0.0.1")
        self._listener = Listener((host, self.first_port + me),
                                  authkey=self.authkey)
        accepted: dict[int, Connection] = {}

        def accept_loop():
            while len(accepted) < self.n_processes - 1 - me:
                conn = self._listener.accept()
                peer = conn.recv()
                accepted[peer] = conn

        acceptor = None
        if me < self.n_processes - 1:
            acceptor = threading.Thread(target=accept_loop, daemon=True)
            acceptor.start()
        # dial every lower-numbered process (it is listening)
        for peer in range(me):
            deadline = time.monotonic() + timeout_s
            while True:
                try:
                    conn = Client((host, self.first_port + peer),
                                  authkey=self.authkey)
                    break
                except (ConnectionError, OSError):
                    if time.monotonic() > deadline:
                        raise TimeoutError(
                            f"process {me}: cannot reach peer {peer} at "
                            f"{host}:{self.first_port + peer}")
                    time.sleep(0.05)
            conn.send(me)
            self.peers[peer] = conn
        if acceptor is not None:
            acceptor.join(timeout=timeout_s)
            if acceptor.is_alive():
                raise TimeoutError(
                    f"process {me}: peers did not all connect within "
                    f"{timeout_s}s (expected {self.n_processes - 1 - me})")
            self.peers.update(accepted)

    def close(self) -> None:
        for conn in self.peers.values():
            try:
                conn.close()
            except Exception:
                pass
        self.peers.clear()
        if self._listener is not None:
            try:
                self._listener.close()
            except Exception:
                pass
            self._listener = None

    # -- bulk-synchronous messaging -----------------------------------------
    def exchange(self, tag: Any, msgs: dict[int, Any]) -> dict[int, Any]:
        """Send ``msgs[peer]`` to every peer, receive one message from each.

        Both directions may carry bulk payloads: sends run on a helper
        thread while this thread receives, so two processes exchanging
        large batches cannot deadlock on full socket buffers.
        """
        if not self.peers:
            return {}
        err: list[BaseException] = []

        def send_all():
            try:
                for peer, conn in self.peers.items():
                    conn.send((tag, msgs.get(peer)))
            except BaseException as e:  # surfaced after the joins
                err.append(e)

        sender = threading.Thread(target=send_all, daemon=True)
        sender.start()
        # bounded recv: a hung peer (or accidentally non-SPMD user code
        # whose exchange schedule diverged) must surface as a diagnostic,
        # not an eternal deadlock — only a cleanly-dead peer raises EOFError
        # on its own
        timeout_s = float(os.environ.get(
            "PATHWAY_CLUSTER_RECV_TIMEOUT", 300.0))
        out: dict[int, Any] = {}
        for peer, conn in self.peers.items():
            if not conn.poll(timeout_s):
                raise TimeoutError(
                    f"cluster peer {peer} unresponsive at exchange "
                    f"{tag!r} (process {self.process_id} waited "
                    f"{timeout_s:.0f}s; peer hung, or the programs "
                    "diverged — graph construction must be deterministic "
                    "across processes). Tune with "
                    "PATHWAY_CLUSTER_RECV_TIMEOUT.")
            rtag, payload = conn.recv()
            if rtag != tag:
                raise RuntimeError(
                    f"cluster protocol skew: process {self.process_id} "
                    f"expected {tag!r} from {peer}, got {rtag!r}")
            out[peer] = payload
        sender.join()
        if err:
            raise err[0]
        return out



_CLUSTER: Cluster | None = None


def get_cluster() -> Cluster | None:
    """Process-wide cluster from PATHWAY_* env (None when single-process).
    Connected lazily on first use; the CLI ``spawn -n N`` sets the env for
    each forked process (cli.py)."""
    global _CLUSTER
    if _CLUSTER is not None:
        return _CLUSTER
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.processes <= 1:
        return None
    _CLUSTER = Cluster(cfg.processes, cfg.process_id, cfg.first_port,
                       os.environ.get("PATHWAY_RUN_ID", ""))
    _CLUSTER.connect()
    return _CLUSTER


def reset_cluster() -> None:
    global _CLUSTER
    if _CLUSTER is not None:
        _CLUSTER.close()
    _CLUSTER = None
