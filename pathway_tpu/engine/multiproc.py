"""Multi-process dataflow execution: the host-level cluster plane.

Rebuild of the reference's timely TCP cluster
(src/engine/dataflow/config.rs:62-120 — ``PATHWAY_PROCESSES`` processes x
``PATHWAY_THREADS`` workers each, sockets at ``127.0.0.1:FIRST_PORT+i``;
CLI ``pathway spawn -n`` forks the same program per process). Every process
runs the IDENTICAL user program (SPMD), so all build the same engine graph
with the same node ids; global logical workers ``[0, P*T)`` are owned in
contiguous blocks of T per process, and rows cross processes only at
operator exchange boundaries.

Two transports carry the frames (engine/wire.py's self-describing columnar
format — length-prefixed byte slabs, the shape timely's ``communication``
crate hands to its sockets, with no pickle round-trip on the row path):

* **tcp** — raw loopback/LAN sockets, ``sendall`` out, ``recv_into`` into a
  reusable per-peer buffer (no per-frame allocation on either side).
* **shm** — for same-host peers (selected automatically, or forced via
  ``PATHWAY_EXCHANGE_TRANSPORT``): a ``multiprocessing.shared_memory`` slab
  ring per direction (``PATHWAY_SHM_RING_BYTES``, 4 slots). The writer
  copies the frame chunks straight into a free slot (no join, no socket
  copy) and rings a 13-byte doorbell on the paired socket — the portable
  stand-in for an eventfd, which unrelated processes cannot share without
  SCM_RIGHTS plumbing; the reader decodes *in place* from the slot's
  memoryview, then releases the slot. Frames larger than a slot fall back
  to the TCP path for that frame.

Device-side data parallelism rides the jax mesh/ICI instead
(parallel/mesh.py); this plane moves host rows and progress barriers, which
are control flow, not tensor math (SURVEY §5 distributed-communication
mapping).
"""

from __future__ import annotations

import errno
import hmac as hmac_mod
import logging
import os
import selectors
import socket
import struct
import time
from typing import Any

from pathway_tpu.engine import wire
from pathway_tpu.engine.locking import assert_unlocked
from pathway_tpu.engine.threads import spawn
from pathway_tpu.testing import faults

logger = logging.getLogger(__name__)

_u32 = struct.Struct("<I")
_u64 = struct.Struct("<Q")
_DOORBELL = struct.Struct("<cIQ")  # b"S" | slot | length
_INLINE_HDR = struct.Struct("<cQ")  # b"F" | length
_SHM_ACK = b"A"  # dialer -> listener: rings attached and token verified

TRANSPORTS = ("tcp", "shm")


class ClusterConnectError(ConnectionError):
    """Cluster wiring failed inside its deadline — a peer never dialed,
    died mid-handshake, or presented a bad authkey. Named so a wedged
    ``connect()`` surfaces as a diagnosis instead of a hang."""


def _stat_block() -> dict:
    return {"bytes_out": 0, "bytes_in": 0, "messages": 0, "rounds": 0,
            "encode_s": 0.0, "decode_s": 0.0, "rows_out": 0, "rows_in": 0}


def _send_exact(sock: socket.socket, data) -> None:
    sock.sendall(data)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket. Raises EOFError on a clean peer
    close — the signal the peer-death path keys on."""
    got = 0
    need = len(view)
    while got < need:
        n = sock.recv_into(view[got:])
        if n == 0:
            raise EOFError("cluster peer closed connection")
        got += n


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    buf = bytearray(n)
    _recv_exact_into(sock, memoryview(buf))
    return buf


def _send_hello(sock: socket.socket, obj: dict) -> None:
    import pickle

    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    _send_exact(sock, _u32.pack(len(blob)) + blob)


def _recv_hello(sock: socket.socket) -> dict:
    import pickle

    (n,) = _u32.unpack(bytes(_recv_exact(sock, 4)))
    if n > 1 << 20:
        raise ClusterConnectError(
            f"absurd hello length {n} — not a pathway-tpu peer?")
    # pwt-ok: PWT306 — cluster hello from a peer this process is about
    # to HMAC-authenticate (engine/wire.py handshake); length-capped
    # metadata dict, not a snapshot restore path
    return pickle.loads(bytes(_recv_exact(sock, n)))


def hmac_handshake(sock: socket.socket, authkey: bytes,
                   deadline: float) -> None:
    """Mutual HMAC-SHA256 challenge over a raw socket (replaces the
    multiprocessing.connection challenge, which needed its Connection
    framing). Both sides write first, then read — no deadlock. The
    per-operation timeout is capped below the caller's deadline so one
    silent dialer (port scanner, peer dying mid-handshake) cannot
    monopolize an accept loop while a genuine peer waits. Shared by the
    cluster exchange plane and the replica-fleet control channel
    (engine/replica.py / engine/router.py)."""
    sock.settimeout(min(5.0, max(0.1, deadline - time.monotonic())))
    my_nonce = os.urandom(16)
    _send_exact(sock, my_nonce)
    peer_nonce = bytes(_recv_exact(sock, 16))
    _send_exact(sock,
                hmac_mod.new(authkey, peer_nonce, "sha256").digest())
    theirs = bytes(_recv_exact(sock, 32))
    mine = hmac_mod.new(authkey, my_nonce, "sha256").digest()
    if not hmac_mod.compare_digest(theirs, mine):
        raise ClusterConnectError(
            "cluster authentication failed (PATHWAY_RUN_ID mismatch "
            "between processes?)")


# -- control-channel framing (replica fleet) ----------------------------------
# The router<->replica control plane ships (tag, payload) messages as
# length-prefixed engine/wire.py frames over an HMAC-authenticated socket —
# the PR-11 wire format and handshake, minus the shm rings (control traffic
# is tiny; heartbeats and scale commands, not row batches).

_CTRL_MAX_FRAME = 16 << 20  # a control message has no business being bigger


def send_control_frame(sock: socket.socket, tag: Any, payload: Any) -> int:
    """One framed control message: u32 total | wire frame. Returns bytes
    put on the wire.

    Fault point ``router.control.partition`` (testing/faults.py): while
    armed, frames silently vanish instead of going on the wire — a
    network partition of the control plane, not a connection death (the
    socket stays up; heartbeats stop arriving, promote commands are
    lost, and the router's staleness detector — not EOF — must notice)."""
    if faults.armed("router.control.partition"):
        try:
            faults.hit("router.control.partition", dir="send", tag=tag)
        except faults.InjectedFault:
            return 0  # partitioned: the frame is dropped on the floor
    chunks, total, _rows = wire.encode_frame(tag, payload)
    _send_exact(sock, b"".join([_u32.pack(total), *chunks]))
    return _u32.size + total


def recv_control_frame(sock: socket.socket) -> tuple[Any, Any]:
    """Read one framed control message; (tag, payload). Raises EOFError
    on clean peer close — the replica-death signal the router keys on.

    The ``router.control.partition`` fault point drops frames on this
    side too (both directions partition): a dropped frame is consumed
    from the socket and discarded, and the read blocks for the next."""
    while True:
        (total,) = _u32.unpack(bytes(_recv_exact(sock, 4)))
        if total > _CTRL_MAX_FRAME:
            raise ClusterConnectError(
                f"absurd control frame length {total} — not a pathway-tpu "
                "control peer?")
        buf = _recv_exact(sock, total)
        tag, payload, _rows = wire.decode_frame(memoryview(buf))
        if faults.armed("router.control.partition"):
            try:
                faults.hit("router.control.partition", dir="recv",
                           tag=tag)
            except faults.InjectedFault:
                continue  # partitioned: drop the frame, keep reading
        return tag, payload


def control_authkey(run_id: str | None = None) -> bytes:
    """The fleet-wide HMAC key: every process of one deployment derives
    it from PATHWAY_RUN_ID (same derivation as the cluster's)."""
    rid = run_id if run_id is not None else os.environ.get(
        "PATHWAY_RUN_ID", "")
    return f"pathway-tpu/{rid or 'cluster'}".encode()


def shm_ring_bytes() -> int:
    try:
        return max(1 << 16,
                   int(os.environ.get("PATHWAY_SHM_RING_BYTES",
                                      str(8 << 20))))
    except ValueError:
        return 8 << 20


def _wire_compat() -> tuple:
    """Native buffer layout this process would put on the wire: byte order
    plus the array itemsizes the columnar codec's bulk buffers use
    (engine/wire.py packs diff/int/float/length arrays native-endian)."""
    import sys
    from array import array

    return (sys.byteorder, array("i").itemsize, array("I").itemsize,
            array("q").itemsize, array("d").itemsize)


def _wire_compat_error(theirs, peer_id: int) -> str | None:
    """None when compatible; otherwise the named refusal. Hellos from
    peers predating the field (None) are treated as compatible — the
    frame magic/version still guards gross protocol skew."""
    if theirs is None or tuple(theirs) == _wire_compat():
        return None
    return (f"peer {peer_id} has an incompatible native wire layout "
            f"{tuple(theirs)} vs {_wire_compat()} (byte order / array "
            "itemsizes): columnar wire format v1 ships native-endian bulk "
            "buffers and refuses cross-endian clusters rather than "
            "decoding corrupt rows")


def transport_mode() -> str:
    """``PATHWAY_EXCHANGE_TRANSPORT``: auto (default — shm for same-host
    peers, tcp across hosts), shm (same-host required; warns and keeps tcp
    if the peer is remote), or tcp (force sockets everywhere)."""
    mode = os.environ.get("PATHWAY_EXCHANGE_TRANSPORT", "auto").lower()
    if mode not in ("auto", "shm", "tcp"):
        logger.warning("unknown PATHWAY_EXCHANGE_TRANSPORT=%r; using auto",
                       mode)
        return "auto"
    return mode


def _shm_headroom() -> int | None:
    """Free bytes on /dev/shm, or None when undeterminable (non-Linux).
    SharedMemory's create ftruncate()s tmpfs sparsely, so an over-capacity
    ring is created "successfully" and the first slot write past the
    limit kills the process with SIGBUS — the only safe check is up
    front. Docker's default /dev/shm is 64 MiB; a 4-process cluster at
    the 8 MiB ring default needs ~96 MiB."""
    try:
        st = os.statvfs("/dev/shm")
    except (AttributeError, OSError):  # pragma: no cover - non-Linux
        return None
    return st.f_bavail * st.f_frsize


class _ShmRing:
    """One direction of a same-host exchange link: a shared-memory slab
    split into ``nslots`` equal slots, each guarded by a 1-byte state flag
    (0 = free, 1 = full). The writer claims slot ``seq % nslots``, copies
    the frame chunks in, flips the flag, and rings the doorbell on the
    paired socket; the reader decodes in place and flips the flag back.
    Single-producer/single-consumer by construction (one direction of one
    peer pair), so the byte-sized flags are the whole protocol — the
    socket doorbell provides the cross-process ordering barrier."""

    _HDR = struct.Struct("<4sIQ")  # magic | nslots | slot_bytes

    def __init__(self, name: str | None = None, *, nslots: int = 4,
                 slot_bytes: int | None = None):
        from multiprocessing import resource_tracker, shared_memory

        if name is None:
            if slot_bytes is None:
                slot_bytes = max(4096, shm_ring_bytes() // nslots)
            size = self._HDR.size + nslots + nslots * slot_bytes
            self._shm = shared_memory.SharedMemory(create=True, size=size)
            self.created = True
            self._HDR.pack_into(self._shm.buf, 0, b"PWSH", nslots,
                                slot_bytes)
            self.nslots = nslots
            self.slot_bytes = slot_bytes
        else:
            # CPython 3.10 registers ATTACHERS with the resource tracker
            # too (bpo-38119), so both sides would unlink at exit
            # (double-unlink noise, and an early unlink if the attacher
            # exits first). Undo the registration AFTER the attach — a
            # global register monkeypatch would race unrelated
            # SharedMemory creates on other threads (their segments would
            # silently lose tracker coverage for the whole patch window).
            self._shm = shared_memory.SharedMemory(name=name)
            try:
                resource_tracker.unregister(self._shm._name,
                                            "shared_memory")
            except Exception:  # pragma: no cover - tracker quirks
                pass
            self.created = False
            magic, self.nslots, self.slot_bytes = self._HDR.unpack_from(
                self._shm.buf, 0)
            if magic != b"PWSH":
                self._shm.close()  # mapped but unusable — do not leak it
                raise ClusterConnectError(
                    f"shared-memory ring {name} has bad magic")
        self.name = self._shm.name
        self._state_off = self._HDR.size
        self._data_off = self._HDR.size + self.nslots
        self._seq = 0

    def _slot_view(self, slot: int) -> memoryview:
        off = self._data_off + slot * self.slot_bytes
        return self._shm.buf[off:off + self.slot_bytes]

    def write(self, chunks: list, total: int,
              deadline: float) -> int | None:
        """Copy ``chunks`` into the next slot; returns the slot index, or
        None when the frame exceeds the slot size (caller sends inline
        over TCP instead). Blocks until the slot is free — a reader that
        never drains surfaces as a TimeoutError, not silent corruption."""
        if total > self.slot_bytes:
            return None
        slot = self._seq % self.nslots
        buf = self._shm.buf
        state_at = self._state_off + slot
        pause = 20e-6
        while buf[state_at]:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shm ring slot {slot} not released within deadline "
                    "(peer hung mid-exchange?)")
            time.sleep(pause)
            pause = min(pause * 2, 0.002)
        view = self._slot_view(slot)
        pos = 0
        for c in chunks:
            ln = len(c)
            view[pos:pos + ln] = c
            pos += ln
        buf[state_at] = 1
        self._seq += 1
        return slot

    def read_view(self, slot: int, length: int) -> memoryview:
        return self._slot_view(slot)[:length]

    # attach-verification token: the listener writes random bytes into
    # slot 0's data region (the slot flag stays free, so the first real
    # frame simply overwrites them) and ships them in the hello reply;
    # the dialer proves the mapping is genuinely the SAME memory by
    # reading them back. Hostname equality alone lies for cloned
    # VMs/containers with a default hostname.
    def poke_token(self, token: bytes) -> None:
        view = self._slot_view(0)
        view[:len(token)] = token

    def peek_token(self, n: int) -> bytes:
        return bytes(self._slot_view(0)[:n])

    def release(self, slot: int) -> None:
        self._shm.buf[self._state_off + slot] = 0

    def close(self) -> None:
        try:
            self._shm.close()
        except Exception as e:  # pragma: no cover - teardown best-effort
            # typically BufferError: a raised frame's traceback still
            # pins a slot view, so the mmap cannot unmap yet — it dies
            # with the process either way
            logger.debug("shm ring %s close failed: %s", self.name, e)
        finally:
            # unlink regardless: it only removes the NAME, and skipping
            # it (the old close-then-unlink chain) leaked the segment on
            # /dev/shm forever whenever close() raised
            if self.created:
                try:
                    self._shm.unlink()
                except FileNotFoundError:
                    pass
                except Exception as e:  # pragma: no cover - teardown
                    logger.debug("shm ring %s unlink failed: %s",
                                 self.name, e)


class _Peer:
    """One duplex cluster link: the TCP socket (frames, doorbells, and the
    handshake) plus optional shared-memory rings for bulk payloads."""

    def __init__(self, sock: socket.socket, transport: str = "tcp",
                 tx_ring: _ShmRing | None = None,
                 rx_ring: _ShmRing | None = None):
        self.sock = sock
        self.transport = transport
        self.tx_ring = tx_ring
        self.rx_ring = rx_ring
        self._rbuf = bytearray(1 << 16)  # reusable inline-frame buffer
        self._sel = selectors.DefaultSelector()
        self._sel.register(sock, selectors.EVENT_READ)

    def send_frame(self, chunks: list, total: int,
                   deadline: float) -> int:
        """Ship one frame; returns bytes put on the wire (shm doorbells
        count their 13 control bytes, not the slab traffic — ``bytes_out``
        measures socket pressure; slab bytes ride ``shm_bytes``)."""
        if self.tx_ring is not None:
            slot = self.tx_ring.write(chunks, total, deadline)
            if slot is not None:
                self.sock.sendall(_DOORBELL.pack(b"S", slot, total))
                return _DOORBELL.size
        hdr = _INLINE_HDR.pack(b"F", total)
        self.sock.sendall(b"".join([hdr, *chunks]))
        return _INLINE_HDR.size + total

    def wait_readable(self, timeout: float) -> bool:
        return bool(self._sel.select(timeout))

    def recv_frame(self):
        """Read one frame. Returns ``(view, release, wire_bytes)`` —
        ``view`` is valid until ``release()`` is called (shm slot, or the
        reusable inline buffer)."""
        hdr = bytes(_recv_exact(self.sock, 1))
        if hdr == b"S":
            rest = _recv_exact(self.sock, _DOORBELL.size - 1)
            slot, length = struct.unpack("<IQ", bytes(rest))
            ring = self.rx_ring
            if ring is None:
                raise RuntimeError(
                    "shm doorbell received but no ring attached "
                    "(transport negotiation skew)")
            view = ring.read_view(slot, length)
            return view, lambda: ring.release(slot), _DOORBELL.size
        if hdr == b"F":
            (length,) = _u64.unpack(
                bytes(_recv_exact(self.sock, _INLINE_HDR.size - 1)))
            if length > len(self._rbuf):
                self._rbuf = bytearray(max(length, 2 * len(self._rbuf)))
            view = memoryview(self._rbuf)[:length]
            _recv_exact_into(self.sock, view)
            return view, _noop, _INLINE_HDR.size + length
        raise RuntimeError(
            f"cluster protocol skew: unknown frame type {hdr!r}")

    def close(self) -> None:
        try:
            self._sel.close()
        except Exception:  # pragma: no cover
            pass
        try:
            self.sock.close()
        finally:
            for ring in (self.tx_ring, self.rx_ring):
                if ring is not None:
                    ring.close()


def _noop() -> None:
    return None


class Cluster:
    """Pairwise duplex connections between the P processes of one run.

    Process ``i`` listens on ``first_port + i``; every ``j > i`` dials
    ``i``. All exchanges are bulk-synchronous: ``exchange(tag, msgs)``
    sends one message to every peer and returns one message from every
    peer, so each call is also a barrier (timely's progress channels
    collapse to this under whole-batch microbatch scheduling).
    """

    def __init__(self, n_processes: int, process_id: int, first_port: int,
                 run_id: str = ""):
        self.n_processes = int(n_processes)
        self.process_id = int(process_id)
        self.first_port = int(first_port)
        self.authkey = control_authkey(run_id)
        self.peers: dict[int, _Peer] = {}
        self._listener: socket.socket | None = None
        # exchange-plane telemetry (bytes/messages/barriers + enc/dec cost
        # per row) for perf work; exported on /metrics as
        # pathway_tpu_exchange_*{transport=...} so the encdec regression
        # the r5 driver caught (1.453 -> 6.495 us/row) is visible per-run
        # AND per-transport. `stats` keeps the cross-transport totals;
        # `stats_by_transport` splits them by link kind. shm slab traffic
        # is accounted as shm_bytes_out/_in (bytes_out/in measure socket
        # bytes); the two directions are SEPARATE keys because the sender
        # thread and the receiving thread update them concurrently — a
        # shared key's `+=` would lose increments (the PWT202 class).
        self.stats = _stat_block()
        self.stats["shm_bytes_out"] = 0
        self.stats["shm_bytes_in"] = 0
        self.stats_by_transport = {t: _stat_block() for t in TRANSPORTS}

    def shm_bytes(self) -> int:
        """Total slab traffic that bypassed the sockets (both directions;
        single-reader sum of the two thread-owned counters)."""
        return self.stats["shm_bytes_out"] + self.stats["shm_bytes_in"]

    def encode_us_per_row(self, transport: str | None = None) -> float:
        st = self.stats if transport is None \
            else self.stats_by_transport[transport]
        return st["encode_s"] * 1e6 / st["rows_out"] if st["rows_out"] \
            else 0.0

    def decode_us_per_row(self, transport: str | None = None) -> float:
        st = self.stats if transport is None \
            else self.stats_by_transport[transport]
        return st["decode_s"] * 1e6 / st["rows_in"] if st["rows_in"] \
            else 0.0

    def transport_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for p in self.peers.values():
            out[p.transport] = out.get(p.transport, 0) + 1
        return out

    # -- wiring --------------------------------------------------------------
    def connect(self, timeout_s: float = 30.0) -> None:
        me = self.process_id
        host = os.environ.get("PATHWAY_CLUSTER_HOST", "127.0.0.1")
        deadline = time.monotonic() + timeout_s
        expect = self.n_processes - 1 - me
        accepted: dict[int, _Peer] = {}
        accept_err: list[BaseException] = []
        acceptor = None
        if expect > 0:
            lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            lsock.bind((host, self.first_port + me))
            lsock.listen(self.n_processes)
            self._listener = lsock

            def accept_loop():
                # every blocking step is bounded by the shared deadline: a
                # dialer that dies mid-handshake (or a port-scanning
                # stranger) costs one logged failure, never a wedged
                # connect() (the old Listener.accept()/conn.recv() pair
                # blocked forever on exactly that)
                try:
                    while len(accepted) < expect:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise ClusterConnectError(
                                f"process {me}: only {len(accepted)} of "
                                f"{expect} peers connected within "
                                f"{timeout_s}s (missing processes "
                                f"{sorted(set(range(me + 1, self.n_processes)) - set(accepted))})")
                        lsock.settimeout(min(0.25, remaining))
                        try:
                            s, _addr = lsock.accept()
                        except socket.timeout:
                            continue
                        try:
                            peer_id, peer = self._handshake_listener(
                                s, deadline)
                        except (OSError, EOFError, ClusterConnectError,
                                socket.timeout) as e:
                            logger.warning(
                                "process %d: dialer handshake failed "
                                "midway (%s); still waiting for %d peers",
                                me, e, expect - len(accepted))
                            s.close()
                            continue
                        accepted[peer_id] = peer
                except BaseException as e:
                    accept_err.append(e)

            acceptor = spawn(accept_loop, name="cluster-acceptor")
        try:
            # dial every lower-numbered process (it is listening)
            for peer in range(me):
                self.peers[peer] = self._dial_peer(
                    host, self.first_port + peer, deadline, timeout_s)
            if acceptor is not None:
                acceptor.join(
                    timeout=max(0.0, deadline - time.monotonic()) + 1.0)
                if accept_err:
                    raise accept_err[0]
                if acceptor.is_alive() or len(accepted) < expect:
                    raise ClusterConnectError(
                        f"process {me}: peers did not all connect within "
                        f"{timeout_s}s (expected {expect}, got "
                        f"{len(accepted)})")
                self.peers.update(accepted)
        except BaseException:
            # failed bring-up must not leak the links already made — in
            # particular accepted peers' shm rings (8 MiB a side), which
            # close() could never reach (they were not in self.peers yet).
            # Stop the acceptor first (closing the listener breaks it out
            # of accept()) so it stops adding to `accepted` under us.
            if self._listener is not None:
                try:
                    self._listener.close()
                except Exception:  # pragma: no cover - teardown
                    pass
                self._listener = None
            if acceptor is not None:
                acceptor.join(timeout=6.0)
            for p in list(accepted.values()):
                try:
                    p.close()
                except Exception:  # pragma: no cover - teardown
                    pass
            self.close()
            raise

    # -- handshake -----------------------------------------------------------
    def _auth(self, sock: socket.socket, deadline: float) -> None:
        hmac_handshake(sock, self.authkey, deadline)

    def _shm_wanted(self) -> bool:
        if transport_mode() == "tcp":
            return False
        try:
            from multiprocessing import shared_memory  # noqa: F401
        except ImportError:  # pragma: no cover - stdlib everywhere we run
            return False
        return True

    def _handshake_listener(self, sock: socket.socket,
                            deadline: float) -> tuple[int, _Peer]:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._auth(sock, deadline)
        hello = _recv_hello(sock)
        peer_id = int(hello["proc"])
        same_host = hello.get("host") == socket.gethostname()
        use_shm = (self._shm_wanted() and hello.get("shm", False)
                   and same_host)
        if transport_mode() == "shm" and not same_host:
            logger.warning(
                "PATHWAY_EXCHANGE_TRANSPORT=shm but peer %d is on another "
                "host (%r); keeping tcp for that link", peer_id,
                hello.get("host"))
        compat_err = _wire_compat_error(hello.get("wire"), peer_id)
        reply: dict[str, Any] = {"proc": self.process_id,
                                 "host": socket.gethostname(),
                                 "wire": _wire_compat(), "shm": None}
        tx = rx = None
        try:
            if use_shm and compat_err is None:
                # the listener (lower process id) creates both rings; the
                # dialer attaches by name. Auto-generated names cannot
                # collide across concurrent runs.
                tx, rx = self._create_rings(peer_id)
            if tx is not None:
                token = os.urandom(16)
                tx.poke_token(token)
                reply["shm"] = {"l2d": tx.name, "d2l": rx.name,
                                "token": token.hex()}
            # the reply ships even on incompatibility so the dialer's own
            # compat check fails fast with the same named diagnosis
            _send_hello(sock, reply)
            if compat_err is not None:
                raise ClusterConnectError(compat_err)
            if tx is not None:
                # wait for the dialer to confirm it attached the rings and
                # verified the token. Without this barrier nothing orders
                # the dialer's peek_token() before this side's first
                # exchange frame lands in slot 0 (a descheduled dialer
                # would read frame bytes and refuse with the cloned-
                # hostname diagnosis on a healthy cluster), and a dialer
                # that refused the rings would leave this listener wedging
                # its first exchange for the full recv timeout. Bounded:
                # the _auth() socket timeout is still armed here.
                if bytes(_recv_exact(sock, 1)) != _SHM_ACK:
                    raise ClusterConnectError(
                        f"peer {peer_id}: bad shared-memory attach ack "
                        "(cluster protocol skew)")
        except BaseException:
            # a dialer dying between ring creation and hello delivery must
            # not leak two mapped-and-linked segments per attempt
            for ring in (tx, rx):
                if ring is not None:
                    ring.close()
            raise
        sock.settimeout(None)
        return peer_id, _Peer(sock, "shm" if tx is not None else "tcp",
                              tx, rx)

    def _create_rings(self, peer_id: int) \
            -> tuple[_ShmRing | None, _ShmRing | None]:
        """Create the ring pair for one accepted dialer, degrading the
        link to tcp (mode auto) or refusing by name (mode shm) when
        /dev/shm cannot hold them. The statvfs precheck matters more than
        the OSError path: tmpfs ftruncate is sparse, so an over-capacity
        create "succeeds" and the first slot write past the limit would
        SIGBUS the process instead of raising anything catchable."""
        slot_bytes = max(4096, shm_ring_bytes() // 4)  # _ShmRing defaults
        need = 2 * (_ShmRing._HDR.size + 4 + 4 * slot_bytes)
        head = _shm_headroom()
        err: str | None = None
        if head is not None and head < need:
            err = (f"/dev/shm has {head} bytes free but the exchange "
                   f"ring pair needs {need}")
        tx = rx = None
        if err is None:
            try:
                tx = _ShmRing()   # listener -> dialer
                rx = _ShmRing()   # dialer -> listener
            except OSError as e:
                if tx is not None:
                    tx.close()
                tx = rx = None
                err = f"cannot create shared-memory ring: {e}"
        if err is not None:
            if transport_mode() == "shm":
                raise ClusterConnectError(
                    f"{err} — shrink PATHWAY_SHM_RING_BYTES or set "
                    "PATHWAY_EXCHANGE_TRANSPORT=tcp")
            logger.warning("%s; keeping tcp for peer %d", err, peer_id)
        return tx, rx

    def _handshake_dialer(self, sock: socket.socket,
                          deadline: float) -> _Peer:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._auth(sock, deadline)
        _send_hello(sock, {"proc": self.process_id,
                           "host": socket.gethostname(),
                           "wire": _wire_compat(),
                           "shm": self._shm_wanted()})
        reply = _recv_hello(sock)
        compat_err = _wire_compat_error(reply.get("wire"),
                                        int(reply.get("proc", -1)))
        if compat_err is not None:
            raise ClusterConnectError(compat_err)
        shm = reply.get("shm")
        tx = rx = None
        if shm is not None:
            tx, rx = self._attach_rings(shm)
            try:
                _send_exact(sock, _SHM_ACK)  # token verified — listener
                # may now let its first exchange frame overwrite slot 0
            except BaseException:
                # listener died between its hello and our ack: the dial
                # loop retries, and each retry would leak another mapped
                # (untracked) ring pair
                tx.close()
                rx.close()
                raise
        sock.settimeout(None)
        return _Peer(sock, "shm" if shm is not None else "tcp", tx, rx)

    def _attach_rings(self, shm: dict) -> tuple[_ShmRing, _ShmRing]:
        """Attach the listener-created ring pair and PROVE the mapping is
        the same memory via the hello token. Hostname equality lies for
        cloned VMs / default-hostname containers: without this check an
        attach failure would be retried as transient until the connect
        deadline, and a name that happens to exist locally would wedge
        the first exchange for the full recv timeout. Both cases are
        definitive — refuse by name (remedy: force tcp)."""
        remedy = ("peers share a hostname but not memory (cloned "
                  "VM/container hostnames?) — set "
                  "PATHWAY_EXCHANGE_TRANSPORT=tcp")
        try:
            rx = _ShmRing(name=shm["l2d"])
        except OSError as e:
            raise ClusterConnectError(
                f"cannot attach peer's shared-memory ring: {e}; "
                f"{remedy}") from e
        try:
            expected = bytes.fromhex(shm.get("token", ""))
            if expected and rx.peek_token(len(expected)) != expected:
                raise ClusterConnectError(
                    f"shared-memory ring attached but its contents do "
                    f"not match the handshake token; {remedy}")
            try:
                tx = _ShmRing(name=shm["d2l"])
            except OSError as e:
                raise ClusterConnectError(
                    f"cannot attach peer's shared-memory ring: {e}; "
                    f"{remedy}") from e
        except BaseException:
            rx.close()
            raise
        return tx, rx

    def _dial_peer(self, host: str, port: int, deadline: float,
                   timeout_s: float) -> _Peer:
        """Dial one lower-numbered peer with a selector wait instead of a
        fixed ``time.sleep(0.05)`` retry poll (the PWT206 exemplar fix): a
        non-blocking connect is awaited on the default selector, so an
        in-progress handshake resolves the instant the peer's listener
        accepts instead of up to one poll interval later. A refused
        connect (the peer's listener is not up yet) resolves immediately
        on loopback, so retries are paced by a bounded selector wait —
        still interruptible by the deadline, never an unconditional
        sleep."""
        sel = selectors.DefaultSelector()
        last_err: Exception | None = None
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ClusterConnectError(
                        f"process {self.process_id}: cannot reach peer at "
                        f"{host}:{port} within {timeout_s}s"
                        + (f" (last error: {last_err})" if last_err else ""))
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.setblocking(False)
                rc = s.connect_ex((host, port))
                if rc in (0, errno.EISCONN):
                    err = 0
                elif rc in (errno.EINPROGRESS, errno.EWOULDBLOCK,
                            errno.EAGAIN, errno.EALREADY):
                    sel.register(s, selectors.EVENT_WRITE)
                    try:
                        ready = sel.select(timeout=remaining)
                    finally:
                        sel.unregister(s)
                    err = (s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                           if ready else errno.ETIMEDOUT)
                else:
                    err = rc
                if err == 0:
                    s.setblocking(True)
                    try:
                        return self._handshake_dialer(s, deadline)
                    except ClusterConnectError:
                        # definitive protocol refusal (authkey mismatch,
                        # cross-endian peer): retrying cannot succeed and
                        # would bury the diagnosis in a timeout message
                        s.close()
                        raise
                    except (OSError, EOFError, socket.timeout) as e:
                        s.close()
                        last_err = e
                else:
                    s.close()
                    last_err = OSError(err, os.strerror(err))
                # pace the retry: an empty-selector timed wait (kernel
                # sleep bounded by the deadline, not a blind time.sleep)
                sel.select(timeout=min(
                    0.05, max(0.0, deadline - time.monotonic())))
        finally:
            sel.close()

    def close(self) -> None:
        # teardown failures are logged (debug, with the peer id), never
        # swallowed silently — a wedged close is how a half-dead cluster
        # teardown stays diagnosable
        for peer, conn in self.peers.items():
            try:
                conn.close()
            except Exception as e:
                logger.debug(
                    "process %d: closing connection to peer %d failed: %s",
                    self.process_id, peer, e)
        self.peers.clear()
        if self._listener is not None:
            try:
                self._listener.close()
            except Exception as e:
                logger.debug("process %d: closing listener failed: %s",
                             self.process_id, e)
            self._listener = None

    # -- bulk-synchronous messaging -----------------------------------------
    def exchange(self, tag: Any, msgs: dict[int, Any]) -> dict[int, Any]:
        """Send ``msgs[peer]`` to every peer, receive one message from each.

        Both directions may carry bulk payloads: sends run on a helper
        thread while this thread receives, so two processes exchanging
        large batches cannot deadlock on full socket buffers.
        """
        if not self.peers:
            return {}
        # fault point: a test arms a Delay here to simulate a peer holding
        # up a tick exchange (the commit-loop stall the watchdog reports)
        faults.hit("cluster.exchange.delay", tag=tag,
                   process_id=self.process_id)
        err: list[BaseException] = []
        st = self.stats
        st["rounds"] += 1
        timeout_s = float(os.environ.get(
            "PATHWAY_CLUSTER_RECV_TIMEOUT", 300.0))
        send_deadline = time.monotonic() + timeout_s

        def send_all():
            try:
                for peer, conn in self.peers.items():
                    ts = self.stats_by_transport[conn.transport]
                    t0 = time.perf_counter()
                    chunks, total, n_rows = wire.encode_frame(
                        tag, msgs.get(peer))
                    enc = time.perf_counter() - t0
                    wire_bytes = conn.send_frame(chunks, total,
                                                 send_deadline)
                    st["encode_s"] += enc
                    ts["encode_s"] += enc
                    st["rows_out"] += n_rows
                    ts["rows_out"] += n_rows
                    st["bytes_out"] += wire_bytes
                    ts["bytes_out"] += wire_bytes
                    if wire_bytes < total:
                        st["shm_bytes_out"] += total
                    st["messages"] += 1
                    ts["messages"] += 1
            except BaseException as e:  # surfaced after the joins
                err.append(e)

        sender = spawn(send_all, name="cluster-sender")
        # bounded recv: a hung peer (or accidentally non-SPMD user code
        # whose exchange schedule diverged) must surface as a diagnostic,
        # not an eternal deadlock — only a cleanly-dead peer raises EOFError
        # on its own
        out: dict[int, Any] = {}
        # socket recv is a known-blocking region: the sanitizer asserts
        # the commit loop entered the exchange holding no engine lock
        assert_unlocked("cluster.exchange.recv")
        for peer, conn in self.peers.items():
            # poll in slices so a LOCAL send failure (unpicklable row,
            # malformed payload) surfaces as itself immediately — in SPMD
            # every process fails identically, so waiting out the full
            # timeout would mislabel it a hung peer
            deadline = time.monotonic() + timeout_s
            while not conn.wait_readable(0.2):
                if err:
                    raise err[0]
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"cluster peer {peer} unresponsive at exchange "
                        f"{tag!r} (process {self.process_id} waited "
                        f"{timeout_s:.0f}s; peer hung, or the programs "
                        "diverged — graph construction must be "
                        "deterministic across processes). Tune with "
                        "PATHWAY_CLUSTER_RECV_TIMEOUT.")
            ts = self.stats_by_transport[conn.transport]
            view, release, wire_bytes = conn.recv_frame()
            t0 = time.perf_counter()
            try:
                rtag, payload, n_rows = wire.decode_frame(view)
            finally:
                release()
            dec = time.perf_counter() - t0
            st["bytes_in"] += wire_bytes
            ts["bytes_in"] += wire_bytes
            if wire_bytes < len(view):
                st["shm_bytes_in"] += len(view)
            if rtag != tag:
                raise RuntimeError(
                    f"cluster protocol skew: process {self.process_id} "
                    f"expected {tag!r} from {peer}, got {rtag!r}")
            st["decode_s"] += dec
            ts["decode_s"] += dec
            st["rows_in"] += n_rows
            ts["rows_in"] += n_rows
            out[peer] = payload
        sender.join()
        if err:
            raise err[0]
        return out


_CLUSTER: Cluster | None = None


def get_cluster() -> Cluster | None:
    """Process-wide cluster from PATHWAY_* env (None when single-process).
    Connected lazily on first use; the CLI ``spawn -n N`` sets the env for
    each forked process (cli.py)."""
    global _CLUSTER
    if _CLUSTER is not None:
        return _CLUSTER
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.processes <= 1:
        return None
    # publish the global only AFTER connect() succeeds: a failed connect
    # close()s the half-built cluster, and a published dead cluster would
    # make every later get_cluster() return it — exchange() sees no peers
    # and silently computes only the local shard instead of erroring
    cluster = Cluster(cfg.processes, cfg.process_id, cfg.first_port,
                      os.environ.get("PATHWAY_RUN_ID", ""))
    cluster.connect()
    import atexit

    # clean shm teardown even when the program never calls reset_cluster:
    # the creator unlinks its rings instead of leaning on the resource
    # tracker's exit sweep (which logs leak warnings)
    atexit.register(reset_cluster)
    _CLUSTER = cluster
    return _CLUSTER


def reset_cluster() -> None:
    global _CLUSTER
    if _CLUSTER is not None:
        _CLUSTER.close()
    _CLUSTER = None
