"""Multi-process dataflow execution: the host-level cluster plane.

Rebuild of the reference's timely TCP cluster
(src/engine/dataflow/config.rs:62-120 — ``PATHWAY_PROCESSES`` processes x
``PATHWAY_THREADS`` workers each, sockets at ``127.0.0.1:FIRST_PORT+i``;
CLI ``pathway spawn -n`` forks the same program per process). Every process
runs the IDENTICAL user program (SPMD), so all build the same engine graph
with the same node ids; global logical workers ``[0, P*T)`` are owned in
contiguous blocks of T per process, and rows cross processes only at
operator exchange boundaries.

Transport is ``multiprocessing.connection`` over loopback/LAN TCP — the
host-side control+exchange plane (the reference's timely ``communication``
crate). Device-side data parallelism rides the jax mesh/ICI instead
(parallel/mesh.py); this plane moves host rows and progress barriers, which
are control flow, not tensor math (SURVEY §5 distributed-communication
mapping).
"""

from __future__ import annotations

import errno
import logging
import os
import pickle
import selectors
import socket
import time
from multiprocessing.connection import (Connection, Listener,
                                        answer_challenge, deliver_challenge)
from typing import Any

from pathway_tpu.engine.locking import assert_unlocked
from pathway_tpu.engine.threads import spawn
from pathway_tpu.internals.keys import Pointer
from pathway_tpu.testing import faults

logger = logging.getLogger(__name__)

_ENTS = "__pw_ents__"


def _pack_payload(obj):
    """Compact the dominant exchange payload shape — lists of
    (Pointer, row, diff) entries — before pickling: Pointers serialize as
    one 16-byte blob per list instead of a per-instance class reconstruct
    (measured: ~3.6x faster dumps, ~25% fewer bytes per row)."""
    if isinstance(obj, list) and obj:
        e = obj[0]
        if (type(e) is tuple and len(e) == 3 and isinstance(e[0], int)
                and not isinstance(e[0], bool)):
            try:
                # the genexpr also validates shape: a non-3-tuple or
                # negative/oversized key raises and the list ships raw
                keys = b"".join(int(k).to_bytes(16, "little")
                                for k, _r, _d in obj)
            except (TypeError, ValueError, OverflowError):
                return obj
            return (_ENTS, keys, [r for _k, r, _d in obj],
                    [d for _k, _r, d in obj])
        return obj
    if isinstance(obj, dict):
        return {k: _pack_payload(v) for k, v in obj.items()}
    return obj


def _payload_rows(obj) -> int:
    """Entry count of a (packed or unpacked) exchange payload — the
    denominator for the per-row encode/decode gauges. Entry lists (and
    packed _ENTS tuples) count their rows; scalars count zero."""
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == _ENTS:
        return len(obj[2])
    if isinstance(obj, list):
        return len(obj)
    if isinstance(obj, dict):
        return sum(_payload_rows(v) for v in obj.values())
    return 0


def _unpack_payload(obj):
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == _ENTS:
        _tag, kb, rows, diffs = obj
        return [
            (Pointer(int.from_bytes(kb[i * 16:(i + 1) * 16], "little")),
             rows[i], diffs[i])
            for i in range(len(rows))
        ]
    if isinstance(obj, dict):
        return {k: _unpack_payload(v) for k, v in obj.items()}
    return obj


class Cluster:
    """Pairwise duplex connections between the P processes of one run.

    Process ``i`` listens on ``first_port + i``; every ``j > i`` dials
    ``i``. All exchanges are bulk-synchronous: ``exchange(tag, msgs)``
    sends one message to every peer and returns one message from every
    peer, so each call is also a barrier (timely's progress channels
    collapse to this under whole-batch microbatch scheduling).
    """

    def __init__(self, n_processes: int, process_id: int, first_port: int,
                 run_id: str = ""):
        self.n_processes = int(n_processes)
        self.process_id = int(process_id)
        self.first_port = int(first_port)
        self.authkey = f"pathway-tpu/{run_id or 'cluster'}".encode()
        self.peers: dict[int, Connection] = {}
        self._listener: Listener | None = None
        self._seq = 0
        # exchange-plane telemetry (bytes/messages/barriers + enc/dec cost
        # per row) for perf work; exported on /metrics as
        # pathway_tpu_exchange_* so the encdec regression the r5 driver
        # caught (1.453 -> 6.495 us/row) is visible per-run
        self.stats = {"bytes_out": 0, "bytes_in": 0, "messages": 0,
                      "rounds": 0, "encode_s": 0.0, "decode_s": 0.0,
                      "rows_out": 0, "rows_in": 0}

    def encode_us_per_row(self) -> float:
        st = self.stats
        return st["encode_s"] * 1e6 / st["rows_out"] if st["rows_out"] \
            else 0.0

    def decode_us_per_row(self) -> float:
        st = self.stats
        return st["decode_s"] * 1e6 / st["rows_in"] if st["rows_in"] \
            else 0.0

    # -- wiring --------------------------------------------------------------
    def connect(self, timeout_s: float = 30.0) -> None:
        me = self.process_id
        host = os.environ.get("PATHWAY_CLUSTER_HOST", "127.0.0.1")
        self._listener = Listener((host, self.first_port + me),
                                  authkey=self.authkey)
        accepted: dict[int, Connection] = {}

        def accept_loop():
            while len(accepted) < self.n_processes - 1 - me:
                conn = self._listener.accept()
                peer = conn.recv()
                accepted[peer] = conn

        acceptor = None
        if me < self.n_processes - 1:
            acceptor = spawn(accept_loop, name="cluster-acceptor")
        # dial every lower-numbered process (it is listening)
        for peer in range(me):
            conn = self._dial_peer(host, self.first_port + peer, timeout_s)
            conn.send(me)
            self.peers[peer] = conn
        if acceptor is not None:
            acceptor.join(timeout=timeout_s)
            if acceptor.is_alive():
                raise TimeoutError(
                    f"process {me}: peers did not all connect within "
                    f"{timeout_s}s (expected {self.n_processes - 1 - me})")
            self.peers.update(accepted)

    def _dial_peer(self, host: str, port: int,
                   timeout_s: float) -> Connection:
        """Dial one lower-numbered peer with a selector wait instead of a
        fixed ``time.sleep(0.05)`` retry poll (the PWT206 exemplar fix): a
        non-blocking connect is awaited on the default selector, so an
        in-progress handshake resolves the instant the peer's listener
        accepts instead of up to one poll interval later. A refused
        connect (the peer's listener is not up yet) resolves immediately
        on loopback, so retries are paced by a bounded selector wait —
        still interruptible by the deadline, never an unconditional
        sleep."""
        deadline = time.monotonic() + timeout_s
        sel = selectors.DefaultSelector()
        last_err: Exception | None = None
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"process {self.process_id}: cannot reach peer at "
                        f"{host}:{port} within {timeout_s}s"
                        + (f" (last error: {last_err})" if last_err else ""))
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.setblocking(False)
                rc = s.connect_ex((host, port))
                if rc in (0, errno.EISCONN):
                    err = 0
                elif rc in (errno.EINPROGRESS, errno.EWOULDBLOCK,
                            errno.EAGAIN, errno.EALREADY):
                    sel.register(s, selectors.EVENT_WRITE)
                    try:
                        ready = sel.select(timeout=remaining)
                    finally:
                        sel.unregister(s)
                    err = (s.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
                           if ready else errno.ETIMEDOUT)
                else:
                    err = rc
                if err == 0:
                    s.setblocking(True)
                    conn = Connection(s.detach())
                    try:
                        # multiprocessing.connection.Client's handshake,
                        # on the socket the selector already connected
                        answer_challenge(conn, self.authkey)
                        deliver_challenge(conn, self.authkey)
                        return conn
                    except (OSError, EOFError) as e:
                        conn.close()
                        last_err = e
                else:
                    s.close()
                    last_err = OSError(err, os.strerror(err))
                # pace the retry: an empty-selector timed wait (kernel
                # sleep bounded by the deadline, not a blind time.sleep)
                sel.select(timeout=min(
                    0.05, max(0.0, deadline - time.monotonic())))
        finally:
            sel.close()

    def close(self) -> None:
        # teardown failures are logged (debug, with the peer id), never
        # swallowed silently — a wedged close is how a half-dead cluster
        # teardown stays diagnosable
        for peer, conn in self.peers.items():
            try:
                conn.close()
            except Exception as e:
                logger.debug(
                    "process %d: closing connection to peer %d failed: %s",
                    self.process_id, peer, e)
        self.peers.clear()
        if self._listener is not None:
            try:
                self._listener.close()
            except Exception as e:
                logger.debug("process %d: closing listener failed: %s",
                             self.process_id, e)
            self._listener = None

    # -- bulk-synchronous messaging -----------------------------------------
    def exchange(self, tag: Any, msgs: dict[int, Any]) -> dict[int, Any]:
        """Send ``msgs[peer]`` to every peer, receive one message from each.

        Both directions may carry bulk payloads: sends run on a helper
        thread while this thread receives, so two processes exchanging
        large batches cannot deadlock on full socket buffers.
        """
        if not self.peers:
            return {}
        # fault point: a test arms a Delay here to simulate a peer holding
        # up a tick exchange (the commit-loop stall the watchdog reports)
        faults.hit("cluster.exchange.delay", tag=tag,
                   process_id=self.process_id)
        err: list[BaseException] = []
        st = self.stats
        st["rounds"] += 1

        def send_all():
            try:
                for peer, conn in self.peers.items():
                    t0 = time.perf_counter()
                    packed = _pack_payload(msgs.get(peer))
                    blob = pickle.dumps(
                        (tag, packed), protocol=pickle.HIGHEST_PROTOCOL)
                    st["encode_s"] += time.perf_counter() - t0
                    st["rows_out"] += _payload_rows(packed)
                    st["bytes_out"] += len(blob)
                    st["messages"] += 1
                    conn.send_bytes(blob)
            except BaseException as e:  # surfaced after the joins
                err.append(e)

        sender = spawn(send_all, name="cluster-sender")
        # bounded recv: a hung peer (or accidentally non-SPMD user code
        # whose exchange schedule diverged) must surface as a diagnostic,
        # not an eternal deadlock — only a cleanly-dead peer raises EOFError
        # on its own
        timeout_s = float(os.environ.get(
            "PATHWAY_CLUSTER_RECV_TIMEOUT", 300.0))
        out: dict[int, Any] = {}
        # socket recv is a known-blocking region: the sanitizer asserts
        # the commit loop entered the exchange holding no engine lock
        assert_unlocked("cluster.exchange.recv")
        for peer, conn in self.peers.items():
            # poll in slices so a LOCAL send failure (unpicklable row,
            # malformed payload) surfaces as itself immediately — in SPMD
            # every process fails identically, so waiting out the full
            # timeout would mislabel it a hung peer
            deadline = time.monotonic() + timeout_s
            while not conn.poll(0.2):
                if err:
                    raise err[0]
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"cluster peer {peer} unresponsive at exchange "
                        f"{tag!r} (process {self.process_id} waited "
                        f"{timeout_s:.0f}s; peer hung, or the programs "
                        "diverged — graph construction must be "
                        "deterministic across processes). Tune with "
                        "PATHWAY_CLUSTER_RECV_TIMEOUT.")
            blob = conn.recv_bytes()
            st["bytes_in"] += len(blob)
            t0 = time.perf_counter()
            rtag, payload = pickle.loads(blob)
            if rtag != tag:
                raise RuntimeError(
                    f"cluster protocol skew: process {self.process_id} "
                    f"expected {tag!r} from {peer}, got {rtag!r}")
            unpacked = _unpack_payload(payload)
            st["decode_s"] += time.perf_counter() - t0
            st["rows_in"] += _payload_rows(unpacked)
            out[peer] = unpacked
        sender.join()
        if err:
            raise err[0]
        return out



_CLUSTER: Cluster | None = None


def get_cluster() -> Cluster | None:
    """Process-wide cluster from PATHWAY_* env (None when single-process).
    Connected lazily on first use; the CLI ``spawn -n N`` sets the env for
    each forked process (cli.py)."""
    global _CLUSTER
    if _CLUSTER is not None:
        return _CLUSTER
    from pathway_tpu.internals.config import get_pathway_config

    cfg = get_pathway_config()
    if cfg.processes <= 1:
        return None
    _CLUSTER = Cluster(cfg.processes, cfg.process_id, cfg.first_port,
                       os.environ.get("PATHWAY_RUN_ID", ""))
    _CLUSTER.connect()
    return _CLUSTER


def reset_cluster() -> None:
    global _CLUSTER
    if _CLUSTER is not None:
        _CLUSTER.close()
    _CLUSTER = None
