"""Engine lock factories + the runtime lock-order sanitizer.

The reference engine gets thread-safety from Rust ownership; this Python
reproduction runs ~10 long-lived threads (device-bridge worker, supervisor
reader threads, watchdog, HTTP monitoring server, multiproc sender/acceptor)
sharing engine state behind ``threading`` primitives. Two layers keep that
honest:

1. **Static** — the PWT2xx concurrency checker
   (internals/static_check/concurrency_check.py) builds a lock inventory and
   a lock-order graph from the source and flags inversions, unguarded
   cross-thread writes, and locks held across blocking calls before they
   become flaky CI failures.
2. **Dynamic** — this module. Every engine lock is created through
   :func:`create_lock` / :func:`create_rlock` / :func:`create_condition`
   (never bare ``threading.Lock()``; the checker flags raw constructions).
   By default the factories return the plain ``threading`` primitive — zero
   overhead. With ``PATHWAY_LOCK_SANITIZER=1`` they return sanitized
   wrappers that record per-thread held-sets, maintain the global lock
   acquisition-order graph, and **assert it stays acyclic**: the first
   acquisition that would create a cycle (the schedule that can deadlock,
   even if this interleaving did not) raises :class:`LockOrderViolation`
   with both acquisition stacks. ``PATHWAY_LOCK_SANITIZER=report`` logs and
   records instead of raising (:func:`violations` returns the findings).

Known-blocking regions — fsync, cluster socket sends, device-bridge
submit/barrier waits — are marked with :func:`blocking_call`; entering one
while holding any sanitized lock reports a held-across-blocking violation
(PWT203's runtime counterpart). ``Condition.wait`` releases its own lock
but blocks while keeping every *other* held lock — the sanitized condition
treats the wait as an implicit blocking region for those.

Lock *names* establish identity in the order graph, so name them by
owner: ``"FlightRecorder._lock"``, ``"DeviceBridge._cv"``. Per-instance
locks of one class share a name deliberately — the order discipline is a
class-level contract, so ``A._x`` nested inside ``B._y`` on one instance
pair and the reverse on another is still detected as a cycle. The known
blind spot of name-level identity: nesting the SAME name (instance 1's
``A._x`` inside instance 2's ``A._x``) records no edge — an instance-
order discipline (e.g. acquire in ``id()`` order) is the caller's
responsibility there, and the engine avoids the pattern entirely (no
code path acquires two instances of one class).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import traceback

logger = logging.getLogger(__name__)

__all__ = [
    "LockOrderViolation", "HeldAcrossBlockingViolation", "assert_unlocked",
    "blocking_call", "create_condition", "create_lock", "create_rlock",
    "held_locks", "sanitizer_enabled", "violations",
]


def sanitizer_enabled() -> bool:
    """Truthy ``PATHWAY_LOCK_SANITIZER`` arms the sanitized factories.
    Checked at lock CREATION time: a run toggles the sanitizer by env, not
    per lock, and the disabled path stays a plain ``threading`` primitive
    with zero wrapper overhead."""
    return os.environ.get("PATHWAY_LOCK_SANITIZER", "").strip().lower() in (
        "1", "true", "on", "yes", "report", "warn")


def _raise_on_violation() -> bool:
    return os.environ.get("PATHWAY_LOCK_SANITIZER", "").strip().lower() \
        not in ("report", "warn")


class LockOrderViolation(RuntimeError):
    """Acquiring this lock here creates a cycle in the global lock
    acquisition-order graph — some interleaving of the involved threads
    deadlocks, even if this run did not."""


class HeldAcrossBlockingViolation(RuntimeError):
    """A known-blocking call (fsync, socket send, bridge submit, condition
    wait) was entered while holding an engine lock: every other thread
    needing that lock now waits out the blocking call too."""


class _SanitizerState:
    """Process-wide sanitizer bookkeeping. One instance per process; tests
    swap in a fresh one via :func:`_reset_for_tests` so the order graph of
    one test cannot poison the next."""

    def __init__(self):
        # guards the order graph + violation list (a plain lock: the
        # sanitizer must not sanitize itself)
        self.mutex = threading.Lock()
        # (held_name, acquired_name) -> short stack of first establishment
        self.edges: dict[tuple[str, str], str] = {}
        self.violation_log: list[dict] = []
        self.tls = threading.local()

    def held_stack(self) -> list:
        stack = getattr(self.tls, "stack", None)
        if stack is None:
            stack = self.tls.stack = []
        return stack


_STATE = _SanitizerState()


def _reset_for_tests() -> None:
    """Fresh order graph + violation list (unit tests only)."""
    global _STATE
    _STATE = _SanitizerState()


def _short_stack(skip: int = 3, limit: int = 6) -> str:
    return "".join(traceback.format_stack()[-(limit + skip):-skip]) or ""


def _has_path(edges: dict, src: str, dst: str) -> bool:
    """Reachability src -> dst in the order graph (iterative DFS)."""
    stack = [src]
    seen = set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(b for (a, b) in edges if a == n)
    return False


def _record_violation(kind: str, message: str,
                      exc_type: type[RuntimeError]) -> None:
    with _STATE.mutex:
        _STATE.violation_log.append(
            {"kind": kind, "message": message, "stack": _short_stack()})
    if _raise_on_violation():
        raise exc_type(message)
    logger.error("lock sanitizer: %s", message)


def violations() -> list[dict]:
    """Violations recorded so far (raise mode records before raising, so
    post-mortems and tests can read the full list either way)."""
    with _STATE.mutex:
        return list(_STATE.violation_log)


def held_locks() -> list[str]:
    """Names of sanitized locks the CALLING thread holds, outermost
    first (empty when the sanitizer is off)."""
    return [w.name for w in _STATE.held_stack()]


class _SanitizedBase:
    """Held-set + order-graph bookkeeping shared by lock and condition
    wrappers. Reentrant holds (RLock, Condition re-entry) push one stack
    entry per acquisition but add no order edges past the first."""

    def __init__(self, name: str):
        self.name = name

    # -- bookkeeping -------------------------------------------------------
    def _on_acquired(self) -> str | None:
        """Record the acquisition; returns an inversion message (without
        raising — the caller must first put the inner lock back) when this
        acquisition would close a cycle in the order graph."""
        stack = _STATE.held_stack()
        if any(w is self for w in stack):
            stack.append(self)  # reentrant: no new edges
            return None
        holders = [w for w in stack if w.name != self.name]
        msg = None
        with _STATE.mutex:
            for held in holders:
                edge = (held.name, self.name)
                if edge in _STATE.edges:
                    continue
                if msg is None and _has_path(_STATE.edges, self.name,
                                             held.name):
                    prior = _STATE.edges.get((self.name, held.name))
                    where = (f"\norder {self.name} -> {held.name} "
                             f"established at:\n{prior}" if prior else "")
                    msg = (
                        f"lock-order inversion: acquiring {self.name!r} "
                        f"while holding {held.name!r}, but the established "
                        f"global order already requires {self.name!r} "
                        f"before {held.name!r} — this schedule can "
                        f"deadlock.{where}")
                # record the edge either way, so every further acquisition
                # through an inverted site reports once, not per call
                _STATE.edges[edge] = _short_stack()
        stack.append(self)
        return msg

    def _on_released(self) -> None:
        stack = _STATE.held_stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return

    def _check_blocking(self, what: str) -> None:
        held = [w.name for w in _STATE.held_stack() if w is not self]
        if held:
            _record_violation(
                "held-across-blocking",
                f"blocking call {what!r} entered while holding engine "
                f"lock(s) {held}: every thread contending on them now "
                f"waits out the blocking call (PWT203)",
                HeldAcrossBlockingViolation)

    def _fail_acquire(self, msg: str, release) -> None:
        # in raise mode the caller never enters its critical section, so
        # the physical lock must be put back BEFORE raising — otherwise
        # the violation wedges every other thread on this lock
        if _raise_on_violation():
            self._on_released()
            release()
        _record_violation("lock-order", msg, LockOrderViolation)


class _SanitizedLock(_SanitizedBase):
    def __init__(self, name: str, inner=None):
        super().__init__(name)
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            msg = self._on_acquired()
            if msg is not None:
                self._fail_acquire(msg, self._inner.release)
        return got

    def release(self) -> None:
        self._on_released()
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<SanitizedLock {self.name!r} {self._inner!r}>"


class _SanitizedRLock(_SanitizedLock):
    def __init__(self, name: str):
        super().__init__(name, threading.RLock())

    def locked(self) -> bool:  # RLock has no locked() before 3.12
        if self._inner.acquire(blocking=False):
            self._inner.release()
            return False
        return True


class _SanitizedCondition(_SanitizedBase):
    """Condition wrapper: the underlying ``threading.Condition`` owns a
    plain inner lock (wait/notify need the real acquire-release protocol);
    this wrapper maintains the held-set and order-graph around it, and
    treats ``wait`` as a blocking region for every OTHER held lock."""

    def __init__(self, name: str):
        super().__init__(name)
        self._cond = threading.Condition()

    def acquire(self, *args) -> bool:
        got = self._cond.acquire(*args)
        if got:
            msg = self._on_acquired()
            if msg is not None:
                self._fail_acquire(msg, self._cond.release)
        return got

    def release(self) -> None:
        self._on_released()
        self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        # wait releases this condition's lock but blocks while every other
        # held lock stays held — exactly the held-across-blocking hazard
        self._check_blocking(f"{self.name}.wait")
        self._on_released()
        try:
            # pwt-ok: PWT205 — delegation; the predicate loop is the
            # caller's obligation (and ITS wait is what PWT205 checks)
            return self._cond.wait(timeout)
        finally:
            self._on_acquired()

    def wait_for(self, predicate, timeout: float | None = None):
        self._check_blocking(f"{self.name}.wait_for")
        self._on_released()
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._on_acquired()

    def notify(self, n: int = 1) -> None:
        # pwt-ok: PWT208 — delegation; the caller's `with cond:` holds
        # the underlying lock when this runs
        self._cond.notify(n)

    def notify_all(self) -> None:
        # pwt-ok: PWT208 — delegation (see notify)
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"<SanitizedCondition {self.name!r}>"


# ---------------------------------------------------------------------------
# factories — the only way engine code creates locks
# ---------------------------------------------------------------------------

def create_lock(name: str):
    """A mutex for engine state. Plain ``threading.Lock`` normally; the
    sanitized wrapper under ``PATHWAY_LOCK_SANITIZER``."""
    if sanitizer_enabled():
        return _SanitizedLock(name)
    return threading.Lock()


def create_rlock(name: str):
    if sanitizer_enabled():
        return _SanitizedRLock(name)
    return threading.RLock()


def create_condition(name: str):
    if sanitizer_enabled():
        return _SanitizedCondition(name)
    return threading.Condition()


def assert_unlocked(what: str) -> None:
    """The held-across-blocking check alone: under the sanitizer, report
    a violation if the calling thread holds any engine lock on the brink
    of the known-blocking call ``what``. Free when the sanitizer is off
    (one env-flag branch)."""
    if sanitizer_enabled():
        held = held_locks()
        if held:
            _record_violation(
                "held-across-blocking",
                f"blocking call {what!r} entered while holding engine "
                f"lock(s) {held}: every thread contending on them now "
                f"waits out the blocking call (PWT203)",
                HeldAcrossBlockingViolation)


@contextlib.contextmanager
def blocking_call(what: str):
    """Mark a known-blocking region (fsync, socket send/recv, bridge
    submit wait, jax dispatch). Under the sanitizer, entering with any
    engine lock held reports a held-across-blocking violation naming the
    locks; otherwise free (one truthiness branch)."""
    assert_unlocked(what)
    yield
