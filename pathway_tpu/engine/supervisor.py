"""Connector supervision: retry/backoff restarts, failure escalation, and
the stall watchdog for the streaming runtime.

Rebuild of the reference engine's treatment of connector failure as
first-class (src/connectors/mod.rs — per-connector input threads whose
death is observed by the main loop): each streaming source runs under a
:class:`ConnectorSupervisor` entry that distinguishes clean end-of-stream
from a crash (``Session.closed_reason``), restarts crashed readers per a
:class:`ConnectorPolicy` with the shared backoff schedule
(internals/retries.py), and — when the retry budget is exhausted — either
terminates the whole runtime re-raising the connector's exception
(``terminate_on_error=True``) or marks the source failed-but-complete and
keeps the rest of the pipeline serving (``terminate_on_error=False``,
failure recorded in the global ErrorLog).

Restarts compose with persistence (engine/persistence.py): the supervisor
counts every entry the reader pushed past its proxy and drops exactly that
prefix from the restarted reader's re-emission, so a restart never
double-delivers — the same replay+skip protocol ``attach_source`` uses for
process restarts, applied in-process. Sources that ``seek`` on attach
re-emit from their seek base, which the per-attempt counter also covers.
Like that protocol, the skip is exact while re-emission is prefix-stable;
input that mutates during the backoff window is best-effort (warned).

The :class:`Watchdog` is a small daemon thread that detects the two hangs
a crash cannot explain: a commit loop that stops progressing (tick
deadline) and a reader that stops producing while claiming liveness (no
push / ``session.sleep`` heartbeat within the stall timeout). Reader
stalls are escalated through the normal failure path — abandon the hung
thread, restart under the policy, then terminate_on_error semantics —
so the watchdog gate actually bites instead of only logging.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from pathway_tpu.internals.retries import (AsyncRetryStrategy,
                                           ExponentialBackoffRetryStrategy,
                                           NoRetryStrategy)

logger = logging.getLogger(__name__)

# entry lifecycle states
RUNNING = "running"     # reader thread live (or not yet observed dead)
BACKOFF = "backoff"     # crashed; restart scheduled at next_restart_at
FAILED = "failed"       # retry budget exhausted; escalated
DONE = "done"           # clean end-of-stream
DETACHED = "detached"   # never started here (replay-only / non-reader peer)


class ConnectorStalledError(RuntimeError):
    """A reader stopped producing while claiming liveness, or hit its
    connect timeout, and the retry budget could not recover it."""


class ConnectorPolicy:
    """Restart/escalation policy for one streaming source.

    ``max_retries`` bounds the number of RESTARTS (the initial run is not a
    retry; ``max_retries=0`` escalates on the first crash). The
    ``retry_strategy`` supplies the backoff schedule via
    ``delay_for_attempt`` — its own ``max_retries`` field is ignored here.
    ``connect_timeout`` (seconds) bounds how long a freshly (re)started
    reader may stay silent — no push, no ``sleep`` heartbeat, no close —
    before the attempt counts as failed.
    """

    def __init__(self, max_retries: int = 3,
                 retry_strategy: AsyncRetryStrategy | None = None,
                 connect_timeout: float | None = None):
        if isinstance(retry_strategy, NoRetryStrategy):
            max_retries = 0
        self.max_retries = max_retries
        self.retry_strategy = retry_strategy or ExponentialBackoffRetryStrategy(
            initial_delay_ms=1000, backoff_factor=2.0, max_delay_ms=30_000)
        self.connect_timeout = connect_timeout

    def __repr__(self) -> str:
        return (f"ConnectorPolicy(max_retries={self.max_retries}, "
                f"retry_strategy={type(self.retry_strategy).__name__}, "
                f"connect_timeout={self.connect_timeout})")


@dataclass
class WatchdogConfig:
    """Stall detection deadlines (seconds). ``tick_deadline_s`` bounds the
    commit loop's inter-tick gap — the default is deliberately generous
    (5 min) because a single slow-but-healthy batch (first-tick JAX
    compilation, a huge drain) must not flip ``/healthz`` to 503 under a
    liveness probe; tighten it per deployment. ``reader_stall_timeout_s``
    (opt-in — sources that legitimately block in user code without
    heartbeating would false-positive) bounds a running reader's
    silence."""

    tick_deadline_s: float | None = 300.0
    reader_stall_timeout_s: float | None = None
    poll_interval_s: float | None = None

    def effective_poll_interval(self) -> float:
        if self.poll_interval_s is not None:
            return self.poll_interval_s
        deadlines = [d for d in (self.tick_deadline_s,
                                 self.reader_stall_timeout_s)
                     if d is not None]
        if not deadlines:
            return 1.0
        return min(1.0, max(0.02, min(deadlines) / 4))


class _SupervisedSession:
    """Reader-facing session for ONE run attempt of a supervised source.

    Duck-types io._datasource.Session. Forwards pushes to the runtime's
    session (or persistence's recording proxy), skipping the first ``skip``
    entries after a restart (the prefix the previous attempts already
    delivered). Records liveness for the watchdog on every push/sleep.
    Once ``detached`` (attempt abandoned: hung reader, connect timeout) it
    drops everything, so a zombie thread can never push into a pipeline
    that moved on without it.
    """

    def __init__(self, entry: "_SupervisedSource", inner, skip: int):
        self._entry = entry
        self._inner = inner
        self._skip = skip
        self.detached = False
        # serializes delivery against detach: _abandon must not return
        # while a push is in flight past the detached check, or the zombie
        # row lands after the restart snapshotted its skip count
        # (double-delivery). Uncontended on the hot path.
        from pathway_tpu.engine.locking import create_lock

        self._lock = create_lock("_SupervisedSession._lock")
        self.closed = threading.Event()
        self.closed_reason: str | None = None
        self.error: BaseException | None = None
        self.stopping = threading.Event()
        if inner.stopping.is_set():
            self.stopping.set()

    @property
    def stop_requested(self) -> bool:
        return self.stopping.is_set()

    def sleep(self, seconds: float) -> bool:
        # detached first: a zombie attempt heartbeating through the shared
        # entry would mask a genuinely hung replacement attempt from the
        # watchdog (and falsify the connect-timeout baseline) forever
        if not self.detached:
            self._entry.touch()
            if self._entry.backpressure.is_set():
                # QoS deferral backpressure: stretch the producer's poll
                # interval while the controller is protecting query
                # latency (engine/qos.py; stop still wakes immediately)
                seconds = seconds \
                    * self._entry.supervisor.backpressure_factor
        return not self.stopping.wait(seconds)

    def push(self, key, row, diff: int = 1, offset=None) -> None:
        with self._lock:
            if self.detached:
                return
            self._entry.touch()
            if self._skip > 0:
                self._skip -= 1
                return
            self._inner.push(key, row, diff, offset=offset)
            self._entry.forwarded += 1

    def drain(self) -> list:
        return self._inner.drain()

    def close(self, reason: str = "eos",
              error: BaseException | None = None) -> None:
        if self.detached:
            return
        if not self.closed.is_set():
            self.closed_reason = reason
            self.error = error
        self.closed.set()


class _SupervisedSource:
    """Supervision state for one streaming source across restarts."""

    def __init__(self, supervisor, node, datasource, session, live_session,
                 policy: ConnectorPolicy, name: str):
        self.supervisor = supervisor
        self.node = node
        self.datasource = datasource
        self.session = session            # the session the runtime drains
        self.live_session = live_session  # what readers push into (may be
        #                                   persistence's recording proxy)
        self.policy = policy
        self.name = name
        self.state = DETACHED
        self.restarts = 0
        self.last_restart_at: float | None = None  # monotonic, stamped on
        #                                            every restart attempt
        self.forwarded = 0  # entries delivered past the proxy, all attempts
        self.stall_count = 0
        self.stalled = False
        # set by the watchdog THREAD, consumed by poll() on the commit
        # loop: an Event, not a bare bool — set/clear/is_set make the
        # cross-thread hand-off explicit (PWT202's fix shape)
        self.stall_flag = threading.Event()
        self.last_error: BaseException | None = None
        self.attempt: _SupervisedSession | None = None
        self.attempt_started_at: float | None = None
        self.last_activity: float | None = None
        # explicit boolean rather than comparing last_activity against
        # attempt_started_at: float equality on a coarse monotonic clock
        # could alias a real first push with "no activity yet"
        self.saw_activity = False
        self.next_restart_at: float | None = None
        self.threads: list[threading.Thread] = []
        # QoS backpressure (engine/qos.py): raised by the supervisor while
        # the controller is deferring this source's ingest — the reader's
        # sleep() stretches so the producer slows at its own cadence. An
        # Event (not a bare bool) for the same PWT202 reason as stall_flag:
        # the commit loop sets it, the reader thread reads it.
        self.backpressure = threading.Event()

    def touch(self) -> None:
        self.last_activity = time.monotonic()
        self.saw_activity = True


class ConnectorSupervisor:
    """Owns every streaming reader thread of one runtime. The runtime calls
    :meth:`poll` once per commit tick; all state transitions happen there
    (single-threaded), the watchdog thread only raises flags."""

    def __init__(self, *, terminate_on_error: bool = True,
                 default_policy: ConnectorPolicy | None = None):
        self.terminate_on_error = terminate_on_error
        self.default_policy = default_policy or ConnectorPolicy()
        self.entries: list[_SupervisedSource] = []
        self.fatal_error: BaseException | None = None
        self.commit_stalled = False  # set/cleared by the watchdog
        # engine-side failure absorbed by the degrade path
        # (terminate_on_error=False): a poisoned device leg or exhausted
        # persistence write retries — serving stopped cleanly but the run
        # must read as degraded, never healthy
        self.engine_failed = False
        self._stopping = False
        # QoS backpressure stretch applied to reader sleeps while the
        # flag is up (engine/qos.py; set by the runtime from QosConfig)
        self.backpressure_factor = 4.0
        # flight recorder (engine/flight_recorder.py), set by the runtime:
        # stall escalations embed its tail so a ConnectorStalledError
        # names what the engine was executing, not just the silent source
        self.recorder = None
        # crash accounting starts at THIS run: a thread that died in a
        # previous run of a long-lived process must not degrade this one
        from pathway_tpu.engine.threads import crash_epoch

        self._crash_epoch = crash_epoch()

    def _stall_error(self, msg: str) -> "ConnectorStalledError":
        rec = self.recorder
        if rec is not None and rec.enabled:
            tail = rec.dump_tail()
            if tail:
                msg += f"\nflight recorder tail:\n{tail}"
        return ConnectorStalledError(msg)

    # -- registration ------------------------------------------------------
    def add_source(self, node, datasource, session, live_session,
                   name: str | None = None) -> _SupervisedSource:
        policy = getattr(datasource, "connector_policy", None) \
            or self.default_policy
        if name is None:
            name = getattr(datasource, "persistent_id", None) \
                or f"{datasource.name}-{datasource._uid}"
        entry = _SupervisedSource(self, node, datasource, session,
                                  live_session, policy, str(name))
        self.entries.append(entry)
        return entry

    def apply_backpressure(self, active: bool) -> None:
        """Raise/clear QoS deferral backpressure on every INGEST source
        (serving sources — those carrying a request tracker slot — are
        the traffic the controller protects, never throttled here).
        Called by the commit loop each tick (engine/qos.py); readers
        observe it at their next sleep()."""
        for entry in self.entries:
            if hasattr(entry.datasource, "request_tracker"):
                continue
            if active:
                entry.backpressure.set()
            else:
                entry.backpressure.clear()

    def start_all(self) -> None:
        for entry in self.entries:
            if entry.state == DETACHED:
                self._start_attempt(entry, skip=0)

    def _start_attempt(self, entry: _SupervisedSource, skip: int) -> None:
        proxy = _SupervisedSession(entry, entry.live_session, skip)
        entry.attempt = proxy
        entry.stalled = False
        entry.stall_flag.clear()
        now = time.monotonic()
        entry.attempt_started_at = now
        entry.last_activity = now
        entry.saw_activity = False
        if entry.restarts:  # a restart, not the initial attach
            entry.last_restart_at = now
        # state flips last: the watchdog only inspects RUNNING entries, so
        # ordering (timestamps first) keeps it from reading a fresh attempt
        # against the previous attempt's last_activity
        entry.state = RUNNING
        thread = entry.datasource.start(proxy)
        entry.threads.append(thread)

    # -- per-tick state machine -------------------------------------------
    def poll(self) -> BaseException | None:
        """Advance every entry's lifecycle; returns the fatal error once an
        escalation under ``terminate_on_error=True`` demands shutdown."""
        now = time.monotonic()
        for entry in self.entries:
            if entry.state == RUNNING:
                self._poll_running(entry, now)
            elif entry.state == BACKOFF:
                if not self._stopping and now >= entry.next_restart_at:
                    entry.restarts += 1
                    # sources that resume from externally-tracked offsets
                    # (restart_resumes=True, e.g. a Kafka consumer group)
                    # re-emit nothing on restart — skipping would silently
                    # drop that many FRESH rows
                    resumes = getattr(entry.datasource, "restart_resumes",
                                      False)
                    skip = 0 if resumes else entry.forwarded
                    logger.info(
                        "restarting source %r (restart %d/%d, skipping %d "
                        "already-delivered entries)", entry.name,
                        entry.restarts, entry.policy.max_retries, skip)
                    if skip and entry.restarts == 1:
                        # same contract as persistence's prefix-replay
                        # resume (attach_source): exact only while the
                        # reader re-emits the identical prefix on restart
                        # (e.g. the source's underlying data did not
                        # mutate between the crash and the restart)
                        logger.warning(
                            "restarting source %r with the prefix-skip "
                            "protocol: the reader is assumed to re-emit "
                            "the identical first %d entries on restart; "
                            "input mutated in the backoff window may be "
                            "dropped or double-applied.",
                            entry.name, skip)
                    self._start_attempt(entry, skip=skip)
        return self.fatal_error

    def _poll_running(self, entry: _SupervisedSource, now: float) -> None:
        attempt = entry.attempt
        if attempt.closed.is_set():
            if attempt.closed_reason == "error":
                self._on_failure(entry, attempt.error, now)
            else:
                entry.state = DONE
                entry.session.close(reason="eos")
            return
        if entry.stall_flag.is_set():
            entry.stall_flag.clear()
            self._abandon(entry)
            self._on_failure(entry, self._stall_error(
                f"source {entry.name!r} stopped producing while claiming "
                f"liveness (no push/heartbeat for "
                f"{now - entry.last_activity:.1f}s)"), now)
            return
        if (entry.policy.connect_timeout is not None
                and not entry.saw_activity
                and now - entry.attempt_started_at
                > entry.policy.connect_timeout):
            self._abandon(entry)
            self._on_failure(entry, self._stall_error(
                f"source {entry.name!r} produced nothing within its "
                f"connect_timeout ({entry.policy.connect_timeout}s)"), now)

    def _abandon(self, entry: _SupervisedSource) -> None:
        """Give up on the current attempt's thread without joining it (a
        hung thread cannot be joined); detach its proxy so late pushes
        from the zombie are dropped, and ask it to stop."""
        attempt = entry.attempt
        if attempt is not None:
            with attempt._lock:  # waits out any in-flight push first
                attempt.detached = True
            attempt.stopping.set()

    def _on_failure(self, entry: _SupervisedSource, error, now: float) -> None:
        if isinstance(error, ConnectorStalledError):
            entry.stalled = True
            entry.stall_count += 1
        entry.last_error = error
        if not self._stopping and entry.restarts < entry.policy.max_retries:
            delay = entry.policy.retry_strategy.delay_for_attempt(
                entry.restarts)
            entry.next_restart_at = now + delay
            entry.state = BACKOFF
            logger.warning(
                "source %r reader failed (%s: %s); restart %d/%d in %.2fs",
                entry.name, type(error).__name__, error, entry.restarts + 1,
                entry.policy.max_retries, delay)
            return
        if self._stopping:
            # a reader crashing because teardown yanked its resources out
            # from under it is shutdown noise, not a permanent source
            # failure — no error-log entry, no misleading escalation line
            entry.state = FAILED
            entry.session.close(reason="error", error=error)
            logger.debug("source %r reader errored during teardown: %s: %s",
                         entry.name, type(error).__name__, error)
            return
        # retry budget exhausted: escalate
        entry.state = FAILED
        from pathway_tpu.internals.error import global_error_log

        global_error_log().log(
            f"connector {entry.name!r} failed after {entry.restarts} "
            f"restart(s): {type(error).__name__}: {error}",
            operator=f"source:{entry.name}", kind="connector")
        if self.terminate_on_error:
            logger.error(
                "source %r failed permanently; terminating the runtime "
                "(terminate_on_error=True)", entry.name)
            if self.fatal_error is None:
                self.fatal_error = error if error is not None else \
                    RuntimeError(f"connector {entry.name!r} failed")
        else:
            logger.error(
                "source %r failed permanently; continuing without it "
                "(terminate_on_error=False)", entry.name)
        # failed-but-complete: close the runtime-facing session so the rest
        # of the pipeline can finish and shut down cleanly — but through a
        # close() that records the error, never a clean end-of-stream
        entry.session.close(reason="error", error=error)

    # -- teardown ----------------------------------------------------------
    def request_stop(self) -> None:
        self._stopping = True
        for entry in self.entries:
            if entry.attempt is not None:
                entry.attempt.stopping.set()

    def all_threads(self) -> list[threading.Thread]:
        return [t for e in self.entries for t in e.threads]

    # -- observability (StatsMonitor / http_server) ------------------------
    def summary(self) -> list[dict]:
        now = time.monotonic()
        out = []
        for e in self.entries:
            out.append({
                "source": e.name,
                "state": e.state,
                "restarts": e.restarts,
                "last_restart_age_s": (round(now - e.last_restart_at, 1)
                                       if e.last_restart_at is not None
                                       else None),
                "forwarded": e.forwarded,
                "stalled": e.stalled,
                "stall_count": e.stall_count,
                # first line only: stall errors carry a multi-line flight
                # recorder tail that belongs in logs, not a status row
                "error": (f"{type(e.last_error).__name__}: {e.last_error}"
                          .splitlines()[0]
                          if e.last_error is not None else None),
            })
        return out

    def healthy(self) -> bool:
        """The single definition of not-degraded, consumed by /healthz:
        no escalated fatal, no stalled commit loop, no absorbed engine
        failure, no failed or stalled source, and no engine thread dead of
        an uncaught exception (engine/threads.py excepthook — a run whose
        watchdog or bridge worker silently died must not read healthy)."""
        from pathway_tpu.engine.threads import crashed_threads

        return (self.fatal_error is None and not self.commit_stalled
                and not self.engine_failed
                and not crashed_threads(self._crash_epoch)
                and not any(e.state == FAILED or e.stalled
                            for e in self.entries))


class Watchdog:
    """Daemon thread detecting a stalled commit loop and hung readers.

    Reads ``runtime.last_tick_at`` (stamped by the commit loop each
    iteration) against ``tick_deadline_s``; a breach sets
    ``supervisor.commit_stalled`` (surfaced by ``/healthz`` as 503) and
    logs — the loop itself is the hung party, so detection is all that is
    possible. Hung readers (``reader_stall_timeout_s``) are flagged on
    their supervisor entry; the commit loop's next ``poll()`` escalates
    through the normal abandon/restart/terminate path.
    """

    def __init__(self, runtime, supervisor: ConnectorSupervisor,
                 config: WatchdogConfig | None = None):
        self.runtime = runtime
        self.supervisor = supervisor
        self.config = config or WatchdogConfig()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._tick_logged = False
        # distinct commit-stall breaches over the watchdog's lifetime
        # (tests assert a legitimately-waiting commit loop never breaches;
        # commit_stalled alone clears itself on recovery)
        self.commit_stall_events = 0
        # snapshot-age breaches: a wedged snapshot loop is NOT a wedged
        # commit loop (commits keep trailing the watermark while the
        # checkpoint tier silently stops bounding recovery time)
        self.snapshot_stall_events = 0
        self._snapshot_logged = False

    def _postmortem(self) -> str:
        """The flight-recorder tail (last ticks + in-flight leg with its
        operator and user frame), or '' when nothing is recording — the
        attribution block every watchdog fire appends to its log line."""
        rec = getattr(self.runtime.scheduler, "recorder", None)
        if rec is None or not rec.enabled:
            return ""
        tail = rec.dump_tail()
        return f"\nflight recorder tail:\n{tail}" if tail else ""

    def start(self) -> None:
        from pathway_tpu.engine.threads import spawn

        self._thread = spawn(self._run, name="watchdog")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        interval = self.config.effective_poll_interval()
        while not self._stop.wait(interval):
            now = time.monotonic()
            self._check_commit_loop(now)
            self._check_readers(now)
            self._check_snapshot_age()

    def _check_commit_loop(self, now: float) -> None:
        deadline = self.config.tick_deadline_s
        if deadline is None:
            return
        last = getattr(self.runtime, "last_tick_at", None)
        if last is None:
            return
        if now - last > deadline:
            self.supervisor.commit_stalled = True
            if not self._tick_logged:
                self._tick_logged = True
                self.commit_stall_events += 1
                # the oldest unresolved device leg is the prime suspect:
                # the commit loop stamps progress on every watermark
                # advance, so a breach means the frontier itself froze.
                # bridge_inflight() survives recording-off; the flight
                # recorder tail (when on) adds the operator + user frame.
                leg = ""
                sched = getattr(self.runtime, "scheduler", None)
                inflight = sched.bridge_inflight() \
                    if hasattr(sched, "bridge_inflight") else None
                if inflight is not None:
                    leg = (f"; oldest unresolved device leg: tick "
                           f"{inflight['tick']}, in flight for "
                           f"{inflight['since_s']}s")
                logger.error(
                    "watchdog: commit loop has not ticked for %.1fs "
                    "(deadline %.1fs) — the scheduler step or a cluster "
                    "exchange is stuck%s%s", now - last, deadline, leg,
                    self._postmortem())
        elif self.supervisor.commit_stalled:
            self.supervisor.commit_stalled = False
            self._tick_logged = False
            logger.warning("watchdog: commit loop progressing again")

    def _check_snapshot_age(self) -> None:
        """Warn when the operator-state snapshot tier stops keeping pace:
        age beyond 3x the configured tick cadence means restarts are
        quietly drifting back toward O(history) replay even though the
        commit loop itself is healthy."""
        tick_cadence = getattr(self.runtime, "_snapshot_every_ticks", 0)
        byte_cadence = getattr(self.runtime, "_snapshot_every_bytes", 0)
        persistence = getattr(self.runtime, "persistence", None)
        if (not tick_cadence and not byte_cadence) or persistence is None:
            return
        if persistence.wal_entries_uncovered == 0:
            # idle stream: no durable entry lies beyond the last
            # generation, so there is nothing a snapshot SHOULD have
            # covered — age grows harmlessly (ticks are free)
            if self._snapshot_logged:
                self._snapshot_logged = False
                logger.info("watchdog: snapshot cadence recovered")
            return
        if tick_cadence:
            lag = (persistence.last_commit_tick
                   - persistence.last_snapshot_tick)
            breach = lag > 3 * tick_cadence
            unit, cadence = "ticks", tick_cadence
        else:
            lag = persistence.wal_bytes_since_snapshot
            breach = lag > 3 * byte_cadence
            unit, cadence = "bytes", byte_cadence
        if breach:
            if not self._snapshot_logged:
                self._snapshot_logged = True
                self.snapshot_stall_events += 1
                logger.warning(
                    "watchdog: operator-state snapshot age is %d %s "
                    "(cadence %d, threshold %d) — the snapshot pass is "
                    "wedged or disabled while commits keep flowing; "
                    "restart time is growing with history again",
                    lag, unit, cadence, 3 * cadence)
        elif self._snapshot_logged:
            self._snapshot_logged = False
            logger.info("watchdog: snapshot cadence recovered")

    def _check_readers(self, now: float) -> None:
        timeout = self.config.reader_stall_timeout_s
        if timeout is None:
            return
        for entry in self.supervisor.entries:
            if entry.state != RUNNING or entry.stall_flag.is_set():
                continue
            attempt = entry.attempt
            if attempt is None or attempt.closed.is_set() \
                    or attempt.stopping.is_set():
                continue
            if entry.threads and not entry.threads[-1].is_alive():
                continue  # thread death is the supervisor's poll to observe
            if entry.last_activity is not None \
                    and now - entry.last_activity > timeout:
                logger.error(
                    "watchdog: source %r claims liveness but produced no "
                    "push/heartbeat for %.1fs (stall timeout %.1fs)%s",
                    entry.name, now - entry.last_activity, timeout,
                    self._postmortem())
                entry.stall_flag.set()
