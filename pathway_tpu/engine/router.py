"""Latency-aware query router for the elastic replica fleet (the front
tier; the replica half lives in engine/replica.py).

One router process fronts N serving processes (read replicas hydrated
from the primary's snapshot + WAL suffix, plus optionally the primary
itself). Replicas dial the router's **control listener** — the PR-11
framed transport: mutual HMAC-SHA256 handshake keyed on
``PATHWAY_RUN_ID``, then length-prefixed ``engine/wire.py`` frames — and
heartbeat their applied tick, staleness and serving quantiles; the
router detects replica death by control-socket EOF *and* by forward
failure.

Queries enter the router's **front HTTP server** and are proxied to one
replica chosen by

* **staleness bound** — replicas whose watermark lag exceeds
  ``PATHWAY_ROUTER_MAX_STALENESS_TICKS`` are bypassed while a fresher
  one exists (availability wins over the bound when none qualifies), then
* **observed latency** — the router keeps per-replica P² p50/p95
  streaming estimators (the PR-6 ``request_tracker`` machinery) over the
  latencies it measures itself, and picks the endpoint with the lowest
  expected cost ``p50 × (1 + inflight)`` (latency-aware least-work). An
  endpoint nobody routed to for ``PATHWAY_ROUTER_REEXPLORE_S`` scores 0
  and is re-explored: a latency estimate seeded during a cold start
  (first queries pay compile/hydration) must not starve it forever.

**Failover**: the router holds each query body until a response arrives;
a connection-level failure marks the endpoint dead and replays the query
on the next-best replica — in-flight queries survive replica death
(idempotent reads; writes stay on the primary).

**Elastic scaling**: the router's SLO burn rate (violation ratio over a
sliding window / error budget, same contract as the PR-6 tracker, same
``PATHWAY_SLO_E2E_MS`` / ``PATHWAY_SLO_ERROR_BUDGET`` knobs) drives an
autoscaler: sustained burn > high-water spawns a replica via the
operator-supplied callback; burn < low-water retires the worst one with
a graceful ``("stop", ...)`` control frame (the replica drains and
exits; the router stops routing to it first).

The router's own monitoring contract matches the engine's
(``/healthz`` / ``/status`` / ``/metrics`` with ``role: "router"``,
served locally on the front port; every other path is proxied), and the
router is additionally the fleet's single observability scrape point
(engine/fleet_observability.py): ``/fleet/metrics`` (every endpoint's
families merged and re-labeled ``{process=,role=}``), ``/fleet/status``
(roles, applied ticks, staleness, burn rates in one JSON) and
``/fleet/trace`` (one clock-aligned Perfetto timeline with cross-process
flow arrows — a failover renders as an arrow from the router into the
rescuing replica's track). Request ids propagate end to end: the router
adopts/mints ``X-Pathway-Request-Id``, forwards it (plus an
``X-Pathway-Hop`` counter) on every attempt incl. failover replays, and
echoes it on every response incl. 503s.
"""

from __future__ import annotations

import collections
import http.client
import itertools
import json
import logging
import os
import socket
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pathway_tpu.engine.fleet_observability import (HOP_HEADER,
                                                    REQUEST_ID_HEADER,
                                                    RouterRequestLog,
                                                    anchor_epoch_wall_us,
                                                    escape_label_value,
                                                    merge_metrics,
                                                    merge_traces)
from pathway_tpu.engine.locking import create_lock
from pathway_tpu.engine.multiproc import (control_authkey, hmac_handshake,
                                          recv_control_frame,
                                          send_control_frame)
from pathway_tpu.engine.request_tracker import P2Quantile
from pathway_tpu.engine.threads import spawn

logger = logging.getLogger(__name__)

# locally-served paths; everything else proxies to a replica. /fleet/*
# is the single scrape point for the whole fleet
# (engine/fleet_observability.py): merged metrics, one-JSON fleet
# status, and the clock-aligned merged Perfetto trace.
_LOCAL_PATHS = ("/healthz", "/status", "/metrics", "/_router",
                "/fleet/metrics", "/fleet/status", "/fleet/trace")

_router_rid_counter = itertools.count(1)


def _mint_router_rid() -> str:
    """A request id minted at the ROUTER for queries that arrived
    without one — the id every downstream hop then adopts."""
    return f"rtr-{os.getpid():x}-{next(_router_rid_counter):06d}"


def _env_int(name: str, default: int) -> int:
    from pathway_tpu.internals.config import _env_int as ei

    return ei(name, default)


def _env_float(name: str, default: float) -> float:
    from pathway_tpu.internals.config import _env_float as ef

    return ef(name, default)


class ReplicaEndpoint:
    """One registered serving process, as the router sees it: identity +
    serving address from the hello, freshness from heartbeats, latency
    from the router's own measurements."""

    def __init__(self, replica_id: str, role: str, host: str | None,
                 port: int | None, sock: socket.socket):
        self.replica_id = replica_id
        self.role = role  # "replica" | "primary"
        self.host = host
        self.port = port
        self.sock = sock  # control socket (stop commands ride it back)
        self.alive = True
        self.retiring = False
        self.applied_tick = 0
        self.primary_watermark = 0
        self.staleness_ticks = 0
        self.generation = 0
        self.monitoring_port: int | None = None
        # fencing epoch / promotion tick from heartbeats (write-path
        # failover: the router re-anchors surviving replicas on these)
        self.fleet_epoch = 0
        self.promotion_tick: int | None = None
        self.last_heartbeat = _time.monotonic()
        self.requests = 0
        self.failures = 0
        self.inflight = 0
        self.last_routed_at = _time.monotonic()
        self.p50 = P2Quantile(0.5)
        self.p95 = P2Quantile(0.95)
        # replica-side serving quantiles from the heartbeat (/status only
        # — routing uses the router-observed estimators above)
        self.reported_p50_ms: float | None = None
        self.reported_p95_ms: float | None = None
        # replica-side SLO burn rate (heartbeat) — /fleet/status in one
        # JSON next to the router's own front-door burn rate
        self.burn_rate: float | None = None
        # monotonic<->wall clock anchor (heartbeat): lets /fleet/trace
        # align this endpoint's monotonic trace timestamps even when its
        # scraped payload predates the fleet meta block
        self.clock: dict | None = None
        # QoS state from the heartbeat (engine/qos.py): budget, queue
        # depth and the shedding flag — the router's steer-away signal
        self.qos: dict | None = None
        # semantic-result-cache watermark + stats from the heartbeat
        # (engine/result_cache.py): the fleet watermark the router's
        # response cache keys on; None until the endpoint reports one
        self.index_version: int | None = None
        self.result_cache: dict | None = None

    def observe(self, ms: float) -> None:
        self.p50.observe(ms)
        self.p95.observe(ms)

    def expected_cost_ms(self, prior_ms: float = 0.0) -> float:
        """Latency-aware least-work score: the observed p50 scaled by
        queued work. An unmeasured endpoint is costed at ``prior_ms``
        (the fleet's median p50, supplied by ``choose()``): still the
        cheapest choice at equal queue depth — it gets explored and
        thereby measured — but the inflight multiplier keeps a burst of
        concurrent queries from ALL herding onto a just-spawned cold
        replica whose first responses are seconds of compile away."""
        p50 = self.p50.value()
        if p50 is None:
            p50 = prior_ms
        return p50 * (1.0 + self.inflight) if p50 \
            else float(self.inflight)

    def apply_heartbeat(self, hb: dict) -> None:
        self.last_heartbeat = _time.monotonic()
        # a heartbeat is proof of life: a transient forward failure
        # (timeout, connect refusal) marks alive=False, and the next
        # heartbeat restores the endpoint to rotation — a genuinely dead
        # process cannot heartbeat, and its control EOF removes it
        self.alive = True
        # role is adopted LIVE: a promoted replica's very next heartbeat
        # says "primary", and that flip is what ends an election
        # (_endpoint_loop compares before/after and tells the router)
        if hb.get("role") in ("replica", "primary"):
            self.role = str(hb["role"])
        if hb.get("fleet_epoch") is not None:
            self.fleet_epoch = int(hb["fleet_epoch"])
        if hb.get("promotion_tick") is not None:
            self.promotion_tick = int(hb["promotion_tick"])
        # late serving endpoint: a replica whose webserver was not up at
        # hello time announces it via heartbeat once it binds
        if (not self.host or not self.port) and hb.get("host") \
                and hb.get("port"):
            self.host, self.port = hb["host"], int(hb["port"])
        self.applied_tick = int(hb.get("applied_tick", self.applied_tick))
        self.primary_watermark = int(hb.get("primary_watermark",
                                            self.primary_watermark))
        self.staleness_ticks = int(hb.get("staleness_ticks",
                                          self.staleness_ticks))
        self.generation = int(hb.get("generation", self.generation))
        if hb.get("monitoring_port"):
            self.monitoring_port = int(hb["monitoring_port"])
        if hb.get("p50_ms") is not None:
            self.reported_p50_ms = float(hb["p50_ms"])
        if hb.get("p95_ms") is not None:
            self.reported_p95_ms = float(hb["p95_ms"])
        if hb.get("burn_rate") is not None:
            self.burn_rate = float(hb["burn_rate"])
        if isinstance(hb.get("clock"), dict):
            self.clock = hb["clock"]
        if isinstance(hb.get("qos"), dict):
            self.qos = hb["qos"]
        if hb.get("index_version") is not None:
            self.index_version = int(hb["index_version"])
        if isinstance(hb.get("result_cache"), dict):
            self.result_cache = hb["result_cache"]

    def is_shedding(self) -> bool:
        """The endpoint's own QoS controller reported active shedding in
        its latest heartbeat — route around it while anyone else can
        serve (availability still wins when everyone sheds)."""
        return bool(self.qos and self.qos.get("shedding"))

    def p50_skew_ms(self) -> float | None:
        """Router-observed p50 minus the replica's self-reported serving
        p50 — the network + proxy overhead in the healthy case. A skew
        that grows past that floor names a clock-drifted or overloaded
        replica BEFORE it breaches SLO: the replica still thinks it is
        fast (its own timeline is compressed or its accept queue is
        eating the wait), while every router-side measurement already
        pays the real latency."""
        p50 = self.p50.value()
        if p50 is None or self.reported_p50_ms is None:
            return None
        return p50 - self.reported_p50_ms

    def summary(self) -> dict:
        return {
            "replica": self.replica_id,
            "role": self.role,
            "endpoint": (f"{self.host}:{self.port}"
                         if self.host and self.port else None),
            "alive": self.alive,
            "retiring": self.retiring,
            "applied_tick": self.applied_tick,
            "staleness_ticks": self.staleness_ticks,
            "generation": self.generation,
            "requests": self.requests,
            "failures": self.failures,
            "inflight": self.inflight,
            "p50_ms": (None if self.p50.value() is None
                       else round(self.p50.value(), 3)),
            "p95_ms": (None if self.p95.value() is None
                       else round(self.p95.value(), 3)),
            "reported_p50_ms": self.reported_p50_ms,
            "reported_p95_ms": self.reported_p95_ms,
            "p50_skew_ms": (None if (skew := self.p50_skew_ms()) is None
                            else round(skew, 3)),
            "burn_rate": self.burn_rate,
            "qos": self.qos,
            "index_version": self.index_version,
            "result_cache": self.result_cache,
        }


class NoReplicaAvailable(ConnectionError):
    """Every registered endpoint is dead or was already tried."""


class QueryRouter:
    """See module doc. ``start()`` brings up the control listener and the
    front HTTP server; both bind ephemeral ports when given 0."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 control_port: int = 0,
                 max_staleness_ticks: int | None = None,
                 slo_ms: float | None = None,
                 error_budget: float | None = None,
                 write_paths: tuple[str, ...] | list[str] | None = None,
                 cache_routes: tuple[str, ...] | list[str] | None = None):
        self.host = host
        self.port = port
        self.control_port = control_port
        # -- write-path failover (promotion orchestration) ------------------
        # path prefixes that mutate primary state: they route to the
        # primary-role endpoint only, 503 (honest Retry-After) during an
        # election, and NEVER fail over to a replica mid-flight (a write
        # replay against a non-primary would fork the timeline)
        if write_paths is None:
            raw = os.environ.get("PATHWAY_ROUTER_WRITE_PATHS", "")
            write_paths = tuple(p.strip() for p in raw.split(",")
                                if p.strip())
        self.write_paths = tuple(write_paths)
        # -- fleet result cache (engine/result_cache.py) --------------------
        # path prefixes whose responses the router may cache against the
        # fleet index-version watermark (heartbeat-fed). Opt-in: only
        # deterministic read routes keyed purely by (method, path, body)
        # qualify — empty (the default) disables the router cache.
        if cache_routes is None:
            raw = os.environ.get("PATHWAY_ROUTER_CACHE_ROUTES", "")
            cache_routes = tuple(p.strip() for p in raw.split(",")
                                 if p.strip())
        self.cache_routes = tuple(cache_routes)
        if self.cache_routes:
            from pathway_tpu.engine.result_cache import RouterResultCache

            self.response_cache = RouterResultCache()
        else:
            self.response_cache = None
        self.election_timeout_s = max(0.05, _env_int(
            "PATHWAY_ROUTER_ELECTION_TIMEOUT_MS", 3000) / 1000.0)
        self.fleet_epoch = 0           # max fencing epoch seen fleet-wide
        self.promotions_total = 0      # elections completed
        self.failover_seconds: float | None = None  # last death→primary-hb
        # active election: {"started_at", "dead", "target", "epoch"} —
        # guarded by _lock; None when a primary is serving writes
        self._election: dict | None = None
        self._write_primary_id: str | None = None
        self.max_staleness_ticks = (
            max_staleness_ticks if max_staleness_ticks is not None
            else _env_int("PATHWAY_ROUTER_MAX_STALENESS_TICKS", 1024))
        self.slo_ms = slo_ms if slo_ms is not None else _env_float(
            "PATHWAY_SLO_E2E_MS", 20.0)
        self.error_budget = max(1e-6, error_budget if error_budget
                                is not None
                                else _env_float("PATHWAY_SLO_ERROR_BUDGET",
                                                0.01))
        self.forward_timeout_s = _env_float(
            "PATHWAY_ROUTER_FORWARD_TIMEOUT_S", 30.0)
        # an endpoint nobody routed to for this long is re-explored (cost
        # 0): a latency estimate seeded during its cold start — first
        # queries pay compile/hydration — must not starve it forever
        self.reexplore_s = _env_float("PATHWAY_ROUTER_REEXPLORE_S", 5.0)
        self._lock = create_lock("QueryRouter._lock")
        self._endpoints: dict[str, ReplicaEndpoint] = {}
        self._stop = threading.Event()
        self._ctrl_sock: socket.socket | None = None
        self._httpd: ThreadingHTTPServer | None = None
        self._threads: list = []
        # -- fleet-wide serving aggregates ---------------------------------
        self._window: collections.deque = collections.deque(
            maxlen=max(16, _env_int("PATHWAY_SLO_WINDOW", 256)))
        self._e2e_p50 = P2Quantile(0.5)
        self._e2e_p95 = P2Quantile(0.95)
        # router-side per-request spans (route/forward/failover stages,
        # engine/fleet_observability.py): the router's track in the
        # merged fleet trace, keyed by the SAME request id the serving
        # process adopts
        self.request_log = RouterRequestLog()
        self.requests_total = 0
        self.failovers_total = 0
        self.unroutable_total = 0  # 503s: no live replica could answer
        self.violations = 0
        # -- autoscaler ----------------------------------------------------
        self._spawn_cb = None
        self._retire_cb = None
        self.min_replicas = 1
        self.max_replicas = 8
        self.scale_high = 1.0
        self.scale_low = 0.05
        self.scale_cooldown_s = 10.0
        self._last_scale_at = 0.0
        self.scale_out_events = 0
        self.scale_in_events = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        ctrl = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ctrl.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ctrl.bind((self.host, self.control_port))
        ctrl.listen(16)
        self.control_port = ctrl.getsockname()[1]
        self._ctrl_sock = ctrl
        self._track_thread(spawn(self._accept_loop,
                                 name="router-control"))
        # slow-path failure detector (write-path failover): heartbeat
        # staleness + election re-drive; cheap when no primary is known
        self._track_thread(spawn(self._election_loop,
                                 name="router-election"))
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _handle(self, method: str) -> None:
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if method == "GET" and path in _LOCAL_PATHS:
                    router._serve_local(self, path)
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                router._serve_proxy(self, method, body)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def do_PUT(self):
                self._handle("PUT")

            def do_PATCH(self):
                self._handle("PATCH")

            def do_DELETE(self):
                self._handle("DELETE")

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._track_thread(spawn(self._httpd.serve_forever,
                                 name="router-front"))
        logger.info("query router up: front %s:%d, control %s:%d",
                    self.host, self.port, self.host, self.control_port)

    def stop(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._ctrl_sock is not None:
            try:
                self._ctrl_sock.close()
            except OSError:
                pass
            self._ctrl_sock = None
        with self._lock:
            eps = list(self._endpoints.values())
        for ep in eps:
            try:
                ep.sock.close()
            except OSError:
                pass
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=3.0)

    def _track_thread(self, t) -> None:
        """Register a router thread for join-at-stop, pruning finished
        ones so endpoint churn (autoscaler cycles, re-registrations)
        does not grow the list without bound. Lock-guarded against
        stop()'s snapshot-and-clear."""
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    # -- control plane -------------------------------------------------------
    def _accept_loop(self) -> None:
        authkey = control_authkey()
        self._ctrl_sock.settimeout(0.25)
        while not self._stop.is_set():
            try:
                sock, _addr = self._ctrl_sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed: shutting down
            try:
                hmac_handshake(sock, authkey, _time.monotonic() + 5.0)
                tag, hello = recv_control_frame(sock)
                if tag != "hello":
                    raise ConnectionError(
                        f"control protocol skew: expected hello, "
                        f"got {tag!r}")
            except Exception as e:  # noqa: BLE001 — strangers knock
                logger.warning("control handshake failed: %s", e)
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            sock.settimeout(None)
            ep = ReplicaEndpoint(
                str(hello.get("replica") or f"anon-{id(sock):x}"),
                str(hello.get("role") or "replica"),
                hello.get("host"), hello.get("port"), sock)
            with self._lock:
                old = self._endpoints.get(ep.replica_id)
                self._endpoints[ep.replica_id] = ep
            if old is not None:
                try:
                    old.sock.close()
                except OSError:
                    pass
            logger.info("replica registered: %s (%s) at %s:%s",
                        ep.replica_id, ep.role, ep.host, ep.port)
            if ep.role == "primary":
                with self._lock:
                    self._write_primary_id = ep.replica_id
            self._track_thread(spawn(
                lambda e=ep: self._endpoint_loop(e),
                name=f"router-hb-{ep.replica_id}"))

    def _endpoint_loop(self, ep: ReplicaEndpoint) -> None:
        """Per-endpoint heartbeat reader; EOF/socket error = death."""
        try:
            while not self._stop.is_set():
                tag, payload = recv_control_frame(ep.sock)
                if tag == "hb" and isinstance(payload, dict):
                    was_primary = ep.role == "primary"
                    ep.apply_heartbeat(payload)
                    self._note_heartbeat(ep, was_primary)
        except (OSError, EOFError, ConnectionError):
            pass
        finally:
            ep.alive = False
            with self._lock:
                if self._endpoints.get(ep.replica_id) is ep:
                    del self._endpoints[ep.replica_id]
            try:
                ep.sock.close()
            except OSError:
                pass
            if not self._stop.is_set():
                logger.warning(
                    "replica %s left the fleet (control link closed) — "
                    "routing around it", ep.replica_id)
                # the WRITE primary died: writes are down until a
                # replica promotes — start the election immediately
                # (control EOF is the fast death signal; the heartbeat
                # staleness monitor is the slow one for partitions)
                self._on_primary_death(ep.replica_id)

    def _note_heartbeat(self, ep: ReplicaEndpoint,
                        was_primary: bool) -> None:
        """Router-side bookkeeping per heartbeat: track the fleet's max
        fencing epoch, learn who the write primary is, and complete an
        election when the promoted candidate's first primary-role
        heartbeat arrives."""
        completed = None
        with self._lock:
            self.fleet_epoch = max(self.fleet_epoch, ep.fleet_epoch)
            if ep.role != "primary":
                return
            if self._write_primary_id != ep.replica_id:
                self._write_primary_id = ep.replica_id
            el = self._election
            if el is not None:
                # the failover clock stops HERE: primary death →
                # first primary-role heartbeat from the rescuer
                self._election = None
                self.promotions_total += 1
                self.failover_seconds = \
                    _time.monotonic() - el["started_at"]
                completed = el
        if completed is not None:
            logger.warning(
                "election complete: %s is the new write primary at "
                "fencing epoch %d (failover %.3fs)", ep.replica_id,
                ep.fleet_epoch, self.failover_seconds)
        if not was_primary or completed is not None:
            # first primary heartbeat (promotion or late role flip):
            # re-anchor every surviving replica on the new timeline
            self._broadcast_reanchor(ep)

    def _broadcast_reanchor(self, primary: ReplicaEndpoint) -> None:
        """Tell every surviving replica to re-anchor its WAL tail on the
        promoted timeline: epoch + the tick the new timeline ends at
        (pending ticks past it are the dead primary's torn final commit,
        truncated from every log by the promotion)."""
        tick = primary.promotion_tick
        if tick is None:
            return  # a born-primary (no promotion): nothing to re-anchor
        for ep in self.endpoints():
            if ep.replica_id == primary.replica_id or ep.role != "replica":
                continue
            try:
                send_control_frame(ep.sock, "reanchor",
                                   {"epoch": primary.fleet_epoch,
                                    "tick": int(tick)})
            except OSError as e:
                logger.warning("reanchor to %s failed: %s",
                               ep.replica_id, e)

    # -- write-path failover: election ---------------------------------------
    def _on_primary_death(self, replica_id: str) -> None:
        """The write primary is gone (control EOF, heartbeat staleness,
        or a failed write forward): open an election and command the
        best candidate to promote. Idempotent — a second death signal
        for the same primary joins the already-running election."""
        with self._lock:
            if self._election is not None \
                    or self._write_primary_id != replica_id:
                return
            self._write_primary_id = None
            self._election = {
                "started_at": _time.monotonic(),
                "dead": replica_id,
                "target": None,
                # the epoch the candidate must claim AT LEAST: strictly
                # above everything the fleet has seen, so the dead
                # primary's stamps can never tie the new timeline's
                "epoch": self.fleet_epoch + 1,
            }
        logger.warning(
            "write primary %s died — electing a successor (timeout "
            "%.1fs; writes 503 until a candidate promotes)",
            replica_id, self.election_timeout_s)
        self._elect()

    def _elect(self) -> None:
        """Pick the most-caught-up live replica and send it the promote
        command. Candidate selection by highest ``applied_tick``: zero
        acknowledged-write loss needs the candidate that tailed the
        most of the dead primary's WAL (any survivor CAN recover the
        full durable prefix by replay, but the freshest one promotes
        fastest). A send failure marks the candidate dead and moves to
        the next; with no candidates the election stays open and the
        monitor retries as replicas (re-)register."""
        with self._lock:
            el = self._election
            if el is None:
                return
            epoch = el["epoch"]
        while True:
            candidates = [e for e in self.endpoints()
                          if e.alive and not e.retiring
                          and e.role == "replica"]
            if not candidates:
                logger.warning(
                    "election open but no live replica candidates — "
                    "writes stay 503 until one registers")
                return
            target = max(candidates, key=lambda e: e.applied_tick)
            try:
                send_control_frame(target.sock, "promote",
                                   {"epoch": epoch,
                                    "dead": self._election["dead"]
                                    if self._election else None})
            except OSError as e:
                logger.warning("promote command to %s failed: %s — "
                               "trying the next candidate",
                               target.replica_id, e)
                target.alive = False
                continue
            with self._lock:
                if self._election is not None:
                    self._election["target"] = target.replica_id
            logger.warning(
                "promote command sent to %s (applied_tick %d, epoch "
                ">= %d)", target.replica_id, target.applied_tick, epoch)
            return

    def _election_loop(self) -> None:
        """Slow-path failure detector + election babysitter. Control
        EOF catches a dead process instantly; this loop catches what
        EOF cannot: a SIGSTOPped/partitioned primary whose socket is
        open but silent (heartbeat staleness), a candidate that died
        mid-promotion (``replica.promote.crash`` — its EOF fires
        _on_primary_death only for primaries, so the election must be
        re-driven here), and a promote frame lost to a control
        partition (re-elected after a full election window of
        silence)."""
        poll_s = max(0.05, self.election_timeout_s / 4.0)
        while not self._stop.wait(poll_s):
            now = _time.monotonic()
            with self._lock:
                el = dict(self._election) if self._election else None
                primary_id = self._write_primary_id
            try:
                if el is None:
                    if primary_id is None:
                        continue
                    ep = self._endpoints.get(primary_id)
                    if ep is not None and now - ep.last_heartbeat \
                            > self.election_timeout_s:
                        # open socket, silent process: a zombie
                        # (SIGSTOP) or a partition — treat as death;
                        # if it resumes later, epoch fencing refuses
                        # its writes and re-registration re-admits it
                        logger.warning(
                            "write primary %s silent for > %.1fs — "
                            "declaring it dead", primary_id,
                            self.election_timeout_s)
                        self._on_primary_death(primary_id)
                    continue
                target = el.get("target")
                tep = self._endpoints.get(target) if target else None
                if tep is None or not tep.alive:
                    # candidate registered dead (or none was chosen):
                    # crash-mid-promotion lands here — elect the next
                    # survivor; its promote() claims a HIGHER epoch, so
                    # the half-promoted corpse can never write
                    self._elect()
                elif now - el["started_at"] > 2 * self.election_timeout_s \
                        and tep.role != "primary":
                    # promote frame (or every heartbeat since) lost:
                    # re-send — promotion is idempotent on the replica
                    logger.warning(
                        "election stalled %.1fs (target %s never "
                        "became primary) — re-electing",
                        now - el["started_at"], target)
                    with self._lock:
                        if self._election is not None:
                            self._election["started_at"] = now
                            self._election["target"] = None
                    self._elect()
            except Exception:  # noqa: BLE001 — the detector must not die
                logger.warning("election evaluation failed",
                               exc_info=True)

    def request_stop_replica(self, ep: ReplicaEndpoint,
                             reason: str = "scale-in") -> bool:
        """Graceful retire: stop routing to the endpoint, then ask it to
        shut down over its control socket."""
        ep.retiring = True
        try:
            send_control_frame(ep.sock, "stop", {"reason": reason})
            return True
        except OSError as e:
            logger.warning("stop command to %s failed: %s",
                           ep.replica_id, e)
            ep.alive = False
            return False

    # -- routing -------------------------------------------------------------
    def endpoints(self) -> list[ReplicaEndpoint]:
        with self._lock:
            return list(self._endpoints.values())

    def live_replicas(self) -> list[ReplicaEndpoint]:
        return [e for e in self.endpoints()
                if e.alive and not e.retiring and e.role == "replica"]

    def choose(self, exclude: set | None = None) -> ReplicaEndpoint:
        """Routing policy (module doc): replicas first (the primary — if
        it registered as a read-serving endpoint — is the last resort),
        within-staleness-bound first, lowest expected latency wins."""
        exclude = exclude or set()
        live = [e for e in self.endpoints()
                if e.alive and not e.retiring
                and e.replica_id not in exclude
                and e.host and e.port]
        if not live:
            raise NoReplicaAvailable(
                "no live replica endpoint (fleet empty, all dead, or "
                "all already tried)")
        replicas = [e for e in live if e.role == "replica"] or live
        # QoS steer-away (engine/qos.py): an endpoint whose heartbeat
        # reports active shedding is bypassed while a non-shedding one
        # exists — the router reacts to the endpoint's OWN admission
        # state before its p95 (a lagging estimator) ever degrades.
        # Availability wins when the whole fleet sheds.
        not_shedding = [e for e in replicas if not e.is_shedding()]
        if not_shedding:
            replicas = not_shedding
        fresh = [e for e in replicas
                 if e.staleness_ticks <= self.max_staleness_ticks]
        if not fresh:
            # availability over the bound: serve from the least-stale
            # endpoint rather than 503 a fleet that is merely lagging
            fresh = sorted(replicas, key=lambda e: e.staleness_ticks)[:1]
        now = _time.monotonic()
        measured = sorted(p for p in (e.p50.value() for e in fresh)
                          if p is not None)
        prior = measured[len(measured) // 2] if measured else 0.0

        def cost(e: ReplicaEndpoint) -> float:
            if now - e.last_routed_at > self.reexplore_s:
                return 0.0  # long-unmeasured: re-explore (see __init__)
            return e.expected_cost_ms(prior)

        chosen = min(fresh, key=cost)
        # stamp at CHOICE time so concurrent clients do not all pile onto
        # one re-explored endpoint before its first response lands
        chosen.last_routed_at = now
        return chosen

    def forward(self, method: str, path: str, body: bytes,
                content_type: str = "application/json",
                rid: str | None = None, hop: int = 0
                ) -> tuple[int, bytes, str, int, str, str, str | None]:
        """Proxy one query, failing over across replicas until one
        answers. Returns (status, body, serving replica id, failovers,
        response content type, request id, retry-after). The query body
        is held here until a response arrives — replica death mid-flight
        costs a retry, never the query.

        Every 503 leaving the router carries ``Retry-After`` (the
        unified shed contract, engine/qos.py): an unroutable/fleet-dead
        503 supplies its own hint, and a backend's shed 503 has its
        upstream ``Retry-After`` propagated instead of dropped with the
        rest of the upstream headers.

        Propagation contract (engine/fleet_observability.py): the
        request id — inbound ``X-Pathway-Request-Id`` or minted here —
        is forwarded with an incremented ``X-Pathway-Hop`` on EVERY
        attempt, including failover replays, so the rescuing replica
        adopts the same id the first attempt carried; the caller echoes
        it on every response, including 503s."""
        if self.is_write_path(path):
            return self._forward_write(method, path, body, content_type,
                                       rid, hop)
        if rid is None:
            rid = _mint_router_rid()
        span = self.request_log.start(rid, path)
        t0 = _time.perf_counter()
        # fleet-wide semantic cache: a hit is served HERE, off the
        # index-version watermark riding the heartbeats — it never
        # touches a primary or replica (engine/result_cache.py)
        cache_key = cache_wm = None
        if self.response_cache is not None and self.is_cache_path(path):
            from pathway_tpu.engine.result_cache import RouterResultCache

            cache_wm = self._fleet_watermark()
            cache_key = RouterResultCache.key(method, path, body)
            hit = self.response_cache.lookup(cache_key, cache_wm)
            if hit is not None:
                status, data, resp_ctype = hit
                ms = (_time.perf_counter() - t0) * 1e3
                with self._lock:
                    self.requests_total += 1
                    self._window.append(ms)
                    self._e2e_p50.observe(ms)
                    self._e2e_p95.observe(ms)
                self.request_log.finish(span, status, "router-cache")
                return (status, data, "router-cache", 0, resp_ctype,
                        rid, None)
        tried: set[str] = set()
        failovers = 0
        last_err: Exception | None = None
        headers = {"Content-Type": content_type,
                   REQUEST_ID_HEADER: rid,
                   HOP_HEADER: str(hop + 1)}
        while True:
            try:
                ep = self.choose(exclude=tried)
            except NoReplicaAvailable:
                self.unroutable_total += 1
                self.request_log.finish(span, 503, None)
                detail = (f" (last error: {last_err})" if last_err else "")
                return (503,
                        f"no replica available{detail}".encode(),
                        "", failovers, "text/plain", rid, "1")
            span.note_routed()
            tried.add(ep.replica_id)
            ep.inflight += 1
            t_attempt = _time.perf_counter()
            try:
                conn = http.client.HTTPConnection(
                    ep.host, ep.port, timeout=self.forward_timeout_s)
                try:
                    conn.request(method, path, body=body or None,
                                 headers=headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    status = resp.status
                    resp_ctype = resp.getheader(
                        "Content-Type", "application/json")
                    retry_after = resp.getheader("Retry-After")
                finally:
                    conn.close()
            # HTTPException covers the replica dying MID-response
            # (IncompleteRead/BadStatusLine are not OSErrors) — exactly
            # the SIGKILL-under-load case; both classes fail over
            except (OSError, http.client.HTTPException) as e:
                # connection-level failure: the replica is gone (or
                # unreachable) — fail over with the SAME body (and the
                # SAME request id: the replay is the same query)
                ep.failures += 1
                ep.alive = False
                last_err = e
                failovers += 1
                self.failovers_total += 1
                span.note_attempt(ep.replica_id, t_attempt, ok=False)
                logger.warning(
                    "forward to %s failed (%s: %s) — failing over",
                    ep.replica_id, type(e).__name__, e)
                continue
            finally:
                ep.inflight = max(0, ep.inflight - 1)
            # per-replica estimators see THIS attempt's latency only —
            # time burned timing out on a corpse must not poison the
            # rescuing replica's p50/p95 (and thereby choose())
            ep.requests += 1
            ep.observe((_time.perf_counter() - t_attempt) * 1e3)
            span.note_attempt(ep.replica_id, t_attempt, ok=True)
            ms = (_time.perf_counter() - t0) * 1e3
            with self._lock:
                self.requests_total += 1
                self._window.append(ms)
                self._e2e_p50.observe(ms)
                self._e2e_p95.observe(ms)
                if ms > self.slo_ms:
                    self.violations += 1
            self.request_log.finish(span, status, ep.replica_id)
            if status == 503 and not retry_after:
                retry_after = "1"  # every 503 carries the hint
            if cache_key is not None and status == 200 \
                    and cache_wm is not None \
                    and self._fleet_watermark() == cache_wm:
                # fill only when the watermark held across the forward —
                # a version bump mid-flight makes the response's vintage
                # ambiguous, and a miss is cheaper than a wrong serve
                self.response_cache.fill(cache_key, cache_wm, status,
                                         data, resp_ctype)
            return (status, data, ep.replica_id, failovers, resp_ctype,
                    rid, retry_after if status == 503 else None)

    def is_write_path(self, path: str) -> bool:
        p = path.split("?", 1)[0]
        return any(p.startswith(w) for w in self.write_paths)

    def is_cache_path(self, path: str) -> bool:
        p = path.split("?", 1)[0]
        return any(p.startswith(c) for c in self.cache_routes) \
            and not self.is_write_path(p)

    def _fleet_watermark(self):
        """Equality token for the fleet's index state: every live
        endpoint's heartbeat-reported ``index_version``. ``None`` — which
        disables both serve and fill — when no endpoint is live or any
        live endpoint has not reported a version (correctness over
        hits: an unversioned endpoint could be mutating unobserved)."""
        with self._lock:
            eps = [(e.replica_id, e.index_version)
                   for e in self._endpoints.values() if e.alive]
        if not eps or any(v is None for _, v in eps):
            return None
        return frozenset(eps)

    def _election_retry_after(self) -> str:
        """Honest Retry-After for write 503s: the remaining election
        window (death already detected, a candidate is promoting) —
        or one full window when no election is running yet."""
        import math

        with self._lock:
            el = self._election
            remaining = (self.election_timeout_s
                         - (_time.monotonic() - el["started_at"])
                         if el is not None else self.election_timeout_s)
        return str(max(1, math.ceil(remaining)))

    def _forward_write(self, method: str, path: str, body: bytes,
                       content_type: str, rid: str | None, hop: int
                       ) -> tuple[int, bytes, str, int, str, str,
                                  str | None]:
        """Write-path routing: primary only, no cross-replica failover.
        During an election the write 503s with the remaining election
        window as ``Retry-After`` — the client's retry lands after the
        promoted primary started serving. A connection-level failure
        marks the primary dead and opens the election itself (the
        control-plane EOF usually beat us here); the write 503s rather
        than replays, because the router cannot know whether the dying
        primary durably logged it (the client's retry is the idempotent
        path — an acknowledged write is durable, an unacknowledged one
        is the client's to re-send)."""
        if rid is None:
            rid = _mint_router_rid()
        span = self.request_log.start(rid, path)
        t0 = _time.perf_counter()
        with self._lock:
            electing = self._election is not None
            primary_id = self._write_primary_id
        ep = self._endpoints.get(primary_id) if primary_id else None
        if electing or ep is None or not ep.alive \
                or not ep.host or not ep.port:
            self.unroutable_total += 1
            self.request_log.finish(span, 503, None)
            why = ("a new primary is being elected" if electing
                   else "no write primary registered")
            return (503, f"write unavailable: {why}".encode(), "", 0,
                    "text/plain", rid, self._election_retry_after())
        span.note_routed()
        ep.inflight += 1
        t_attempt = _time.perf_counter()
        try:
            conn = http.client.HTTPConnection(
                ep.host, ep.port, timeout=self.forward_timeout_s)
            try:
                conn.request(method, path, body=body or None,
                             headers={"Content-Type": content_type,
                                      REQUEST_ID_HEADER: rid,
                                      HOP_HEADER: str(hop + 1)})
                resp = conn.getresponse()
                data = resp.read()
                status = resp.status
                resp_ctype = resp.getheader("Content-Type",
                                            "application/json")
                retry_after = resp.getheader("Retry-After")
            finally:
                conn.close()
        except (OSError, http.client.HTTPException) as e:
            ep.failures += 1
            ep.alive = False
            logger.warning(
                "write forward to primary %s failed (%s: %s) — opening "
                "election; the client must retry",
                ep.replica_id, type(e).__name__, e)
            self._on_primary_death(ep.replica_id)
            self.unroutable_total += 1
            self.request_log.finish(span, 503, None)
            return (503,
                    f"write primary died mid-request ({e}); retry after "
                    f"failover".encode(),
                    "", 0, "text/plain", rid,
                    self._election_retry_after())
        finally:
            ep.inflight = max(0, ep.inflight - 1)
        ep.requests += 1
        ep.observe((_time.perf_counter() - t_attempt) * 1e3)
        span.note_attempt(ep.replica_id, t_attempt, ok=True)
        ms = (_time.perf_counter() - t0) * 1e3
        with self._lock:
            self.requests_total += 1
            self._window.append(ms)
            self._e2e_p50.observe(ms)
            self._e2e_p95.observe(ms)
            if ms > self.slo_ms:
                self.violations += 1
        self.request_log.finish(span, status, ep.replica_id)
        if status == 503 and not retry_after:
            retry_after = "1"
        return (status, data, ep.replica_id, 0, resp_ctype, rid,
                retry_after if status == 503 else None)

    # -- SLO / scaling -------------------------------------------------------
    def burn_rate(self) -> float:
        """Observed violation ratio over the sliding window / allowed
        error budget — the PR-6 burn-rate contract, measured at the
        fleet's front door."""
        with self._lock:
            if not self._window:
                return 0.0
            viol = sum(1 for v in self._window if v > self.slo_ms)
            return (viol / len(self._window)) / self.error_budget

    def quantiles_ms(self) -> dict | None:
        p50, p95 = self._e2e_p50.value(), self._e2e_p95.value()
        if p50 is None:
            return None
        return {"p50": round(p50, 3), "p95": round(max(p50, p95), 3)}

    def configure_autoscaler(self, spawn_cb=None, retire_cb=None, *,
                             min_replicas: int = 1, max_replicas: int = 8,
                             high: float = 1.0, low: float = 0.05,
                             cooldown_s: float = 10.0,
                             interval_s: float = 1.0) -> None:
        """Arm burn-rate-driven elasticity. ``spawn_cb()`` must start one
        new replica process (it registers itself over the control
        channel); ``retire_cb(replica_id)`` is notified after a graceful
        stop command went out. Evaluation runs on a router thread every
        ``interval_s``."""
        self._spawn_cb = spawn_cb
        self._retire_cb = retire_cb
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.scale_high = high
        self.scale_low = low
        self.scale_cooldown_s = cooldown_s
        self._track_thread(spawn(
            lambda: self._autoscale_loop(interval_s),
            name="router-autoscaler"))

    def _autoscale_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.maybe_scale()
            except Exception:  # noqa: BLE001 — scaling must not die
                logger.warning("autoscaler evaluation failed",
                               exc_info=True)

    def maybe_scale(self) -> str | None:
        """One scaling decision ('out', 'in' or None) based on the
        current burn rate; cooldown-guarded so one burst cannot thrash
        the fleet."""
        now = _time.monotonic()
        if now - self._last_scale_at < self.scale_cooldown_s:
            return None
        live = self.live_replicas()
        burn = self.burn_rate()
        if burn > self.scale_high and self._spawn_cb is not None \
                and len(live) < self.max_replicas:
            logger.info(
                "burn rate %.2f > %.2f with %d replica(s) — scaling OUT",
                burn, self.scale_high, len(live))
            self._last_scale_at = now
            self.scale_out_events += 1
            self._spawn_cb()
            return "out"
        if burn < self.scale_low and len(live) > self.min_replicas:
            # retire the endpoint contributing least: worst observed p95
            victim = max(live, key=lambda e: e.p95.value() or 0.0)
            logger.info(
                "burn rate %.2f < %.2f with %d replica(s) — scaling IN "
                "(retiring %s)", burn, self.scale_low, len(live),
                victim.replica_id)
            self._last_scale_at = now
            self.scale_in_events += 1
            self.request_stop_replica(victim)
            if self._retire_cb is not None:
                self._retire_cb(victim.replica_id)
            return "in"
        return None

    # -- monitoring surface --------------------------------------------------
    def status_payload(self) -> dict:
        qs = self.quantiles_ms()
        el = self._election  # one read: the election thread swaps it
        return {
            "role": "router",
            "front": f"{self.host}:{self.port}",
            "control": f"{self.host}:{self.control_port}",
            "replicas": [e.summary() for e in self.endpoints()],
            "requests": self.requests_total,
            "failovers": self.failovers_total,
            "unroutable": self.unroutable_total,
            "violations": self.violations,
            "slo_ms": self.slo_ms,
            "error_budget": self.error_budget,
            "burn_rate": round(self.burn_rate(), 3),
            "max_staleness_ticks": self.max_staleness_ticks,
            "e2e_ms": qs,
            "scale_out_events": self.scale_out_events,
            "scale_in_events": self.scale_in_events,
            "fleet_epoch": self.fleet_epoch,
            "write_primary": self._write_primary_id,
            "promotions": self.promotions_total,
            "failover_seconds": (
                None if self.failover_seconds is None
                else round(self.failover_seconds, 6)),
            "election": dict(el) if el is not None else None,
            "result_cache": (
                None if self.response_cache is None else {
                    **self.response_cache.stats(),
                    "routes": list(self.cache_routes),
                    "watermark_live": self._fleet_watermark() is not None,
                }),
        }

    def healthz_payload(self) -> tuple[bool, dict]:
        live = [e for e in self.endpoints() if e.alive]
        healthy = bool(live)
        return healthy, {
            "status": "healthy" if healthy else "degraded",
            "role": "router",
            "replicas_live": len(live),
            "replicas": sorted(e.replica_id for e in live),
            "burn_rate": round(self.burn_rate(), 3),
        }

    def metrics_payload(self) -> str:
        esc = escape_label_value  # the one exposition-escaping contract
        eps = self.endpoints()
        lines = [
            "# TYPE pathway_tpu_router_replicas gauge",
            f"pathway_tpu_router_replicas "
            f"{sum(1 for e in eps if e.alive)}",
            "# TYPE pathway_tpu_router_requests_total counter",
            f"pathway_tpu_router_requests_total {self.requests_total}",
            "# TYPE pathway_tpu_router_failovers counter",
            f"pathway_tpu_router_failovers {self.failovers_total}",
            "# TYPE pathway_tpu_router_unroutable counter",
            f"pathway_tpu_router_unroutable {self.unroutable_total}",
            "# TYPE pathway_tpu_router_scale_out_events counter",
            f"pathway_tpu_router_scale_out_events {self.scale_out_events}",
            "# TYPE pathway_tpu_router_scale_in_events counter",
            f"pathway_tpu_router_scale_in_events {self.scale_in_events}",
            "# TYPE pathway_tpu_slo_target_ms gauge",
            f"pathway_tpu_slo_target_ms {self.slo_ms}",
            "# TYPE pathway_tpu_slo_burn_rate gauge",
            f"pathway_tpu_slo_burn_rate {round(self.burn_rate(), 6)}",
            # write-path failover: max fencing epoch seen fleet-wide and
            # elections completed — a promotion shows as the epoch gauge
            # stepping and the counter incrementing together
            "# TYPE pathway_tpu_fleet_epoch gauge",
            f"pathway_tpu_fleet_epoch {self.fleet_epoch}",
            "# TYPE pathway_tpu_promotions_total counter",
            f"pathway_tpu_promotions_total {self.promotions_total}",
        ]
        if self.failover_seconds is not None:
            # last primary-death → first-primary-heartbeat wall clock
            lines.append("# TYPE pathway_tpu_failover_seconds gauge")
            lines.append(f"pathway_tpu_failover_seconds "
                         f"{round(self.failover_seconds, 6)}")
        if self.response_cache is not None:
            # fleet-level semantic result cache (engine/result_cache.py):
            # hits served at the router off heartbeat watermarks
            rc = self.response_cache.stats()
            lines += [
                "# TYPE pathway_tpu_router_cache_hits counter",
                f"pathway_tpu_router_cache_hits {rc['hits']}",
                "# TYPE pathway_tpu_router_cache_misses counter",
                f"pathway_tpu_router_cache_misses {rc['misses']}",
                "# TYPE pathway_tpu_router_cache_invalidations counter",
                f"pathway_tpu_router_cache_invalidations "
                f"{rc['invalidations']}",
                "# TYPE pathway_tpu_router_cache_entries gauge",
                f"pathway_tpu_router_cache_entries {rc['entries']}",
                "# TYPE pathway_tpu_router_cache_hit_ratio gauge",
                f"pathway_tpu_router_cache_hit_ratio "
                f"{round(rc['hit_ratio'], 6)}",
            ]
        if eps:
            lines.append("# TYPE pathway_tpu_router_requests counter")
            lines.append("# TYPE pathway_tpu_router_failures counter")
            lines.append("# TYPE pathway_tpu_router_replica_p50_ms gauge")
            lines.append("# TYPE pathway_tpu_router_replica_p95_ms gauge")
            lines.append(
                "# TYPE pathway_tpu_router_replica_p50_skew_ms gauge")
            lines.append(
                "# TYPE pathway_tpu_replica_staleness_ticks gauge")
            lines.append("# TYPE pathway_tpu_replica_applied_tick gauge")
            lines.append(
                "# TYPE pathway_tpu_replica_index_version gauge")
            for e in sorted(eps, key=lambda e: e.replica_id):
                lab = f'{{replica="{esc(e.replica_id)}"}}'
                lines.append(
                    f"pathway_tpu_router_requests{lab} {e.requests}")
                lines.append(
                    f"pathway_tpu_router_failures{lab} {e.failures}")
                p50, p95 = e.p50.value(), e.p95.value()
                if p50 is not None:
                    lines.append(
                        "pathway_tpu_router_replica_p50_ms"
                        f"{lab} {round(p50, 6)}")
                    lines.append(
                        "pathway_tpu_router_replica_p95_ms"
                        f"{lab} {round(max(p50, p95), 6)}")
                skew = e.p50_skew_ms()
                if skew is not None:
                    # router-observed minus self-reported serving p50:
                    # a clock-drifted or overloaded replica shows here
                    # before it breaches SLO (heartbeats already carry
                    # the replica's own quantiles)
                    lines.append(
                        "pathway_tpu_router_replica_p50_skew_ms"
                        f"{lab} {round(skew, 6)}")
                lines.append(
                    f"pathway_tpu_replica_staleness_ticks{lab} "
                    f"{e.staleness_ticks}")
                lines.append(
                    f"pathway_tpu_replica_applied_tick{lab} "
                    f"{e.applied_tick}")
                if e.index_version is not None:
                    # the watermark the router's response cache keys on
                    lines.append(
                        f"pathway_tpu_replica_index_version{lab} "
                        f"{e.index_version}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- fleet surfaces (engine/fleet_observability.py) ----------------------
    def _scrape(self, url: str, timeout: float = 2.5) -> str:
        import urllib.request

        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode()

    def _scrapable_endpoints(self) -> list[ReplicaEndpoint]:
        return [e for e in self.endpoints()
                if e.alive and e.monitoring_port]

    def _scrape_fleet(self, path: str, timeout: float
                      ) -> list[tuple[ReplicaEndpoint, str]]:
        """Scrape ``path`` from every alive endpoint's monitoring port
        CONCURRENTLY — N endpoints cost one timeout of wall time, not N
        (a hung-but-alive endpoint must not serialize the whole fleet
        scrape behind its timeout); failures degrade to that endpoint's
        rows only. Results keep endpoint order."""
        import concurrent.futures

        eps = self._scrapable_endpoints()
        if not eps:
            return []

        def one(ep: ReplicaEndpoint) -> str | None:
            host = ep.host or "127.0.0.1"
            try:
                return self._scrape(
                    f"http://{host}:{ep.monitoring_port}{path}",
                    timeout=timeout)
            except Exception as e:  # noqa: BLE001 — a dead endpoint is
                # routing's problem; the scrape degrades per-process
                logger.warning("fleet scrape of %s%s failed: %s",
                               ep.replica_id, path, e)
                return None
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(eps)),
                thread_name_prefix="pathway-tpu-fleet-scrape") as pool:
            bodies = list(pool.map(one, eps))
        return [(ep, body) for ep, body in zip(eps, bodies)
                if body is not None]

    def fleet_metrics_payload(self) -> str:
        """``/fleet/metrics``: one scrape point for the whole fleet —
        the router's own families plus every registered endpoint's
        ``/metrics`` body, merged under the exposition contract
        (one TYPE line per family, every sample re-labeled
        ``process=``/``role=``, counters/histograms summed under
        ``process="_fleet"``; fleet_observability.merge_metrics)."""
        scrapes = [({"process": "router", "role": "router"},
                    self.metrics_payload())]
        for ep, text in self._scrape_fleet("/metrics", timeout=2.5):
            scrapes.append(({"process": ep.replica_id, "role": ep.role},
                            text))
        return merge_metrics(scrapes)

    def fleet_status_payload(self) -> dict:
        """``/fleet/status``: roles, applied ticks, staleness and burn
        rates of the whole fleet in one JSON — built from the control-
        channel heartbeats (no scrape round trip), plus the router's own
        front-door aggregates and per-request stage summary."""
        fleet = [e.summary() for e in self.endpoints()]
        qs = self.quantiles_ms()
        return {
            "role": "router",
            "front": f"{self.host}:{self.port}",
            "requests": self.requests_total,
            "failovers": self.failovers_total,
            "unroutable": self.unroutable_total,
            "slo_ms": self.slo_ms,
            "burn_rate": round(self.burn_rate(), 3),
            "e2e_ms": qs,
            "request_stages": self.request_log.stage_summary(),
            "fleet_epoch": self.fleet_epoch,
            "write_primary": self._write_primary_id,
            "promotions": self.promotions_total,
            "failover_seconds": (
                None if self.failover_seconds is None
                else round(self.failover_seconds, 6)),
            "electing": self._election is not None,
            "fleet": fleet,
        }

    def chrome_trace_payload(self) -> dict:
        """The router's own mergeable trace payload: the request track
        (route/forward/failover spans per query) plus the fleet meta
        block, same shape every serving process exposes at
        ``/trace?format=chrome``."""
        return {
            "traceEvents": self.request_log.chrome_trace_events(),
            "displayTimeUnit": "ms",
            "pathway_meta": {
                "pid": os.getpid(),
                "process": "router",
                "role": "router",
                "epoch_wall_us": self.request_log.epoch_wall_us,
            },
        }

    def fleet_trace_payload(self) -> dict:
        """``/fleet/trace``: ONE clock-aligned Perfetto timeline for the
        fleet — the router's request track merged with every registered
        endpoint's ``/trace?format=chrome`` payload; a failover renders
        as a flow arrow from the router into the rescuing replica's
        track (fleet_observability.merge_traces)."""
        payloads = [self.chrome_trace_payload()]
        for ep, body in self._scrape_fleet("/trace?format=chrome",
                                           timeout=5.0):
            try:
                payload = json.loads(body)
            except ValueError as e:
                logger.warning("fleet trace payload of %s unparseable: "
                               "%s", ep.replica_id, e)
                continue
            meta = payload.get("pathway_meta")
            clock = ep.clock
            if isinstance(meta, dict) and not meta.get("epoch_wall_us") \
                    and isinstance(clock, dict) \
                    and {"wall", "perf"} <= set(clock):
                # endpoint shipped no wall anchor in the payload: fall
                # back to the control-channel heartbeat anchor — its
                # (wall - perf) offset plus the payload's perf epoch
                # pins the same wall-clock origin the recorder would
                # have stamped
                try:
                    meta["epoch_wall_us"] = anchor_epoch_wall_us(
                        clock, float(meta.get("epoch_perf", 0.0) or 0.0))
                except (TypeError, ValueError):
                    pass  # version-skewed junk anchor: merge unaligned
            payloads.append(payload)
        return merge_traces(payloads)

    # -- front HTTP plumbing -------------------------------------------------
    def _serve_local(self, handler, path: str) -> None:
        if path == "/healthz":
            healthy, payload = self.healthz_payload()
            body = json.dumps(payload).encode()
            code, ctype = (200 if healthy else 503), "application/json"
        elif path == "/metrics":
            body = self.metrics_payload().encode()
            code, ctype = 200, "text/plain; version=0.0.4"
        elif path == "/fleet/metrics":
            body = self.fleet_metrics_payload().encode()
            code, ctype = 200, "text/plain; version=0.0.4"
        elif path == "/fleet/status":
            body = json.dumps(self.fleet_status_payload()).encode()
            code, ctype = 200, "application/json"
        elif path == "/fleet/trace":
            body = json.dumps(self.fleet_trace_payload()).encode()
            code, ctype = 200, "application/json"
        else:  # /status, /_router
            body = json.dumps(self.status_payload()).encode()
            code, ctype = 200, "application/json"
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def _serve_proxy(self, handler, method: str, body: bytes) -> None:
        try:
            hop = int(handler.headers.get(HOP_HEADER) or 0)
        except ValueError:
            hop = 0
        (status, data, replica_id, failovers, ctype, rid,
         retry_after) = self.forward(
            method, handler.path, body,
            content_type=handler.headers.get("Content-Type",
                                             "application/json"),
            rid=handler.headers.get(REQUEST_ID_HEADER) or None, hop=hop)
        try:
            handler.send_response(status)
            handler.send_header("Content-Type", ctype)
            handler.send_header("Content-Length", str(len(data)))
            # the id rides EVERY response — healthy proxies, failover
            # replays AND 503s: an unrouted query must still be
            # greppable fleet-wide by the id its client holds
            handler.send_header(REQUEST_ID_HEADER, rid)
            if retry_after is not None:
                # unified 503 contract: shed (propagated from the
                # backend's QoS gate), unroutable and fleet-dead 503s
                # all tell the client when to come back
                handler.send_header("Retry-After", retry_after)
            if replica_id:
                handler.send_header("X-Pathway-Replica", replica_id)
            if failovers:
                handler.send_header("X-Pathway-Failovers", str(failovers))
            handler.end_headers()
            handler.wfile.write(data)
        except OSError:
            pass  # client went away; the query itself was served
