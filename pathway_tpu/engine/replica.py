"""Snapshot-hydrated read replicas (the replica half of the elastic
replica fleet; the router half lives in engine/router.py).

A replica is a fresh serving process running the SAME program as the
primary, pointed at the primary's persistence root with
``pw.run(replica_of=<root>)``. It never ingests live data and never
writes durability state; instead it

1. **hydrates** — loads the newest valid operator-state snapshot
   generation (PR-10's restore path: KNN state is re-uploaded to the
   device, never re-embedded; a corrupt newest generation falls back one
   generation, loudly), then
2. **tails** the primary's durability log: each source's WAL is polled
   read-only for records past the replica's applied tick, and every
   COMPLETE primary commit tick is applied locally (a poll round's ready
   ticks coalesce into one scheduler tick — incremental operators are
   additive over deltas, so the coalesced apply lands byte-identically
   on the newest ready tick's state) — the replica's state at
   ``applied_tick`` is byte-identical to the primary's state at the
   same watermark tick, and
3. **serves** — its own ``rest_connector`` routes run live (a
   :class:`~pathway_tpu.io.http.RestSource` sets ``replica_serve_live``)
   so ``query_as_of_now`` answers queries at the replica's applied tick;
   writes stay on the primary.

The primary's root is opened through
``PersistenceDriver(config, read_only=True)``: any append, truncation,
compaction or snapshot write raises
:class:`~pathway_tpu.engine.persistence.ReadOnlyPersistenceError` by
name — a replica structurally cannot damage the primary's WAL or
snapshot generations.

**Tick-boundary rule.** The primary appends one record per source per
commit, all carrying the same watermark tick; a tailer polling mid-commit
could observe source A's record at tick *t* before source B's lands. The
tailer therefore holds the NEWEST observed tick back until a later tick
appears (a completeness proof: the primary's single commit loop finishes
every append of commit *t* before starting *t+1*) or several consecutive
polls read no new bytes (sustained silence: the commit that produced *t*
finished), and only complete ticks are applied — the replica never
serves a state the primary never had at a tick boundary.

Control traffic to the router (registration, heartbeats carrying
applied tick / staleness / serving quantiles, scale-in stop commands)
rides the PR-11 framed transport: HMAC-SHA256 mutual handshake keyed on
``PATHWAY_RUN_ID`` + length-prefixed ``engine/wire.py`` frames
(:func:`~pathway_tpu.engine.multiproc.send_control_frame`).
"""

from __future__ import annotations

import logging
import os
import socket
import time as _time

from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.locking import create_lock
from pathway_tpu.engine.persistence import (PersistenceDriver,
                                            ReadOnlyPersistenceError,
                                            record_epoch, scan_log_bytes,
                                            source_id)
from pathway_tpu.engine.threads import spawn

logger = logging.getLogger(__name__)

__all__ = [
    "ReplicaHydrationError", "ReadOnlyPersistenceError", "ReplicaTailer",
    "ControlClient", "replica_id_from_env",
]


class ReplicaHydrationError(RuntimeError):
    """The replica could not reach a query-ready state from the primary's
    persistence root (unsupported backend, graph mismatch, ...)."""


def replica_id_from_env() -> str:
    return os.environ.get("PATHWAY_REPLICA_ID") or f"replica-{os.getpid()}"


def _poll_interval_s() -> float:
    from pathway_tpu.internals.config import _env_int

    return max(1, _env_int("PATHWAY_REPLICA_POLL_MS", 50)) / 1000.0


class _FsLogTail:
    """Incremental read-only tail over one source's filesystem WAL.

    Tracks (inode, byte offset) so each poll reads ONLY appended bytes.
    A torn/in-flight tail record is left unconsumed (the next poll
    retries once the primary's fsync lands). A compaction (the primary
    atomically replaces the file, changing the inode) or a post-crash
    torn-tail truncation (size below our offset) triggers a rescan from
    byte 0, deduplicated by the per-log strictly-increasing record
    ticks."""

    def __init__(self, path: str):
        self.path = path
        self._ino: int | None = None
        self._offset = 0
        self.last_tick = 0  # max record tick ever returned (dedup key)
        # set when an inode change forced a rescan: the primary replaced
        # the file (compaction) — pump() must verify no tick this tail
        # still needed was truncated away (see ReplicaTailer.pump)
        self.rescanned = False

    def poll(self) -> tuple[list[tuple[int, list]], int]:
        """(new records with tick > last_tick, bytes CONSUMED). The
        progress figure counts parsed bytes, not bytes read: a torn tail
        record re-read on every poll makes no progress, and reporting it
        as activity would reset the quiet-poll counter forever — holding
        the newest complete tick back for as long as the crashed
        primary's torn record sits there."""
        try:
            st = os.stat(self.path)
        except FileNotFoundError:
            return [], 0
        if self._ino is not None and (st.st_ino != self._ino
                                      or st.st_size < self._offset):
            # compacted (atomic replace, new inode) or torn-tail
            # truncated in place (size shrank): rescan from byte 0
            if st.st_ino != self._ino:
                self.rescanned = True
            self._ino, self._offset = None, 0
        if st.st_size <= self._offset:
            return [], 0
        with open(self.path, "rb") as f:
            if self._offset:
                f.seek(self._offset)
            data = f.read()
        if self._ino is None:
            self._ino = st.st_ino
        records, consumed = scan_log_bytes(data,
                                           expect_magic=self._offset == 0)
        self._offset += consumed
        fresh = [r for r in records if r[0] > self.last_tick]
        if fresh:
            self.last_tick = max(r[0] for r in fresh)
        return fresh, consumed


class _MockLogTail:
    """Tail over a MockLog record list (in-process tests): the list is
    shared with the writing driver, so new appends simply appear; an
    in-place truncate_to shrinks it, handled by the tick dedup."""

    def __init__(self, store: dict, sid: str):
        self._records = store.setdefault(sid, [])
        self.last_tick = 0

    def poll(self) -> tuple[list[tuple[int, list]], int]:
        fresh = [r for r in list(self._records) if r[0] > self.last_tick]
        if fresh:
            self.last_tick = max(r[0] for r in fresh)
        return fresh, sum(len(r[1]) for r in fresh)


class ReplicaTailer:
    """Hydration + WAL tailing for one replica runtime (see module doc).

    Lifecycle (driven by StreamingRuntime in replica mode):
    ``bind(runtime)`` classifies sources into tailed (sid has a WAL in
    the root, not a serving source) vs live; ``hydrate(scheduler)``
    restores the newest valid snapshot generation; ``pump(runtime, tc)``
    is called every commit-loop iteration and applies each complete new
    primary tick as one scheduler tick."""

    def __init__(self, backend, replica_id: str | None = None):
        from pathway_tpu import persistence as _p

        if isinstance(backend, str):
            backend = _p.Backend.filesystem(backend)
        if backend.kind not in ("filesystem", "mock"):
            raise ReplicaHydrationError(
                f"replica hydration requires a filesystem (or mock) "
                f"persistence root, not {backend.kind!r}")
        self.replica_id = replica_id or replica_id_from_env()
        self.driver = PersistenceDriver(_p.Config(backend=backend),
                                        read_only=True)
        self._lock = create_lock("ReplicaTailer._lock")
        self._quiet_polls = 0  # consecutive polls that read no bytes
        self._tails: dict[str, object] = {}     # sid -> log tail
        self._nodes: dict[str, object] = {}     # sid -> source Node
        self._tailed_idx: set[int] = set()      # session indices tailed
        # ticks observed but not yet applied: tick -> {sid: entries}
        self._pending: dict[int, dict[str, list]] = {}
        # -- fleet-visible state (stats(), heartbeats, /metrics) -----------
        self.applied_tick = 0        # primary watermark fully applied
        self.primary_watermark = 0   # newest durable tick observed
        self.generation = 0          # snapshot generation hydrated from
        self.fleet_epoch = 0         # newest fencing epoch observed
        self.hydrate_wall_s: float | None = None
        self.catchup_wall_s: float | None = None  # start -> first caught-up
        self.records_applied = 0
        self.entries_applied = 0
        self._started_at = _time.monotonic()
        # set by reanchor() when this replica provably applied state the
        # post-promotion timeline does not contain; pump() raises it so
        # the process dies loudly and a restart re-hydrates whole
        self._poisoned: str | None = None

    # -- wiring -------------------------------------------------------------
    def bind(self, sessions) -> None:
        """Classify the runtime's sources: a source whose durable id has
        a WAL under the primary root is TAILED (its reader thread never
        starts; rows arrive from the log); serving sources
        (``replica_serve_live``) and sources unknown to the root run
        live."""
        root_sids = set(self.driver.list_source_ids())
        for i, (node, _session, ds) in enumerate(sessions):
            if getattr(ds, "replica_serve_live", False):
                continue
            sid = source_id(ds)
            if sid not in root_sids:
                # the primary creates each WAL lazily, on the source's
                # FIRST append — a quiet feed (e.g. a durable-ack write
                # route before its first write) has no file yet. Tail
                # the future path anyway (the tail polls until the file
                # appears); reading LIVE here would double-ingest the
                # feed the moment the primary's log shows up, and a
                # promotion would silently skip re-attaching it.
                logger.warning(
                    "replica source %r has no WAL under the primary "
                    "root yet — tailing its path for the log to appear",
                    sid)
            self._nodes[sid] = node
            self._tailed_idx.add(i)
            if self.driver.kind == "mock":
                self._tails[sid] = _MockLogTail(
                    self.driver._backend._mock_store, sid)
            else:
                self._tails[sid] = _FsLogTail(
                    self.driver.stream_path(sid))
        if not self._tails:
            logger.warning(
                "replica %s: no tailed sources (primary root empty or "
                "ids mismatched?) — serving whatever local state exists",
                self.replica_id)

    def is_tailed(self, session_index: int) -> bool:
        return session_index in self._tailed_idx

    # -- hydration ----------------------------------------------------------
    def hydrate(self, scheduler) -> int:
        """Restore the newest valid snapshot generation into the fresh
        scheduler (operator state incl. the KNN index via re-upload +
        consolidated sink re-emission) and position the tailer past the
        covered prefix. Returns the snapshot tick (0 = no snapshot; the
        whole WAL replays through the first pumps instead)."""
        t0 = _time.perf_counter()
        snap = self.driver.load_snapshot()
        if snap is None:
            self.hydrate_wall_s = _time.perf_counter() - t0
            return 0
        payload = snap["payload"]
        if payload.get("graph") != scheduler.graph_fingerprint():
            raise ReplicaHydrationError(
                "the primary's operator-state snapshot was taken by a "
                "DIFFERENT pipeline (graph fingerprint mismatch) — a "
                "replica must run the identical program as its primary")
        scheduler.restore_operator_states(payload["nodes"])
        scheduler.emit_restored_outputs(snap["tick"])
        tick = int(snap["tick"])
        self.applied_tick = tick
        self.primary_watermark = max(self.primary_watermark, tick)
        self.generation = int(snap["generation"])
        for tail in self._tails.values():
            tail.last_tick = max(tail.last_tick, tick)
        self.hydrate_wall_s = _time.perf_counter() - t0
        logger.info(
            "replica %s hydrated from snapshot generation %d (tick %d) "
            "in %.3fs — tailing the WAL suffix", self.replica_id,
            self.generation, tick, self.hydrate_wall_s)
        return tick

    # -- tailing ------------------------------------------------------------
    def pump(self, runtime, time_counter: int) -> int:
        """One tail round: poll every source's WAL, merge new records
        into the pending buffer, apply every COMPLETE primary tick (see
        module doc for the newest-tick hold-back rule). Returns the
        advanced local tick counter.

        All ready ticks of one round are COALESCED into a single local
        scheduler tick: the incremental operators are additive over
        ``(key, row, diff)`` deltas, so applying Δt1+…+Δtk in one step
        lands byte-identically on the state at tick tk — a state the
        primary had — while paying ONE tick of engine overhead instead
        of k. A replica whose loop was busy serving a slow query batch
        therefore catches up on its backlog in one tick rather than
        stalling new queries behind k sequential applies (bounded tail
        latency AND bounded staleness under load)."""
        if self._poisoned is not None:
            raise ReplicaHydrationError(self._poisoned)
        new_bytes = 0
        rescan_floor: int | None = None  # min seen-tick of rescanned tails
        with self._lock:
            for sid, tail in self._tails.items():
                seen_before = tail.last_tick
                records, nbytes = tail.poll()
                new_bytes += nbytes
                if getattr(tail, "rescanned", False):
                    # what this tail had read BEFORE the replacement is
                    # what bounds the loss — the rescan poll itself
                    # already advanced last_tick through the new file
                    tail.rescanned = False
                    rescan_floor = (seen_before if rescan_floor is None
                                    else min(rescan_floor, seen_before))
                for rec in records:
                    self._pending.setdefault(rec[0], {})[sid] = rec[1]
                    self.fleet_epoch = max(self.fleet_epoch,
                                           record_epoch(rec))
            if self._pending:
                self.primary_watermark = max(self.primary_watermark,
                                             max(self._pending))
            newest = max(self._pending) if self._pending else 0
            # newest-tick hold-back: apply tick t once a LATER tick is
            # durable (the single commit loop finishes every append of
            # commit t before starting t+1 — a later tick anywhere is a
            # completeness PROOF) or after several consecutive quiet
            # polls (the per-commit appends land back-to-back, so a
            # sustained silence means the commit that produced t
            # finished; multiple polls guard against one source's fsync
            # or write-retry straddling a single poll interval — a
            # primary stalled longer than that mid-commit is the
            # residual window only a commit-complete WAL marker would
            # close)
            self._quiet_polls = self._quiet_polls + 1 if new_bytes == 0 \
                else 0
            quiet = self._quiet_polls >= 3
            ready = sorted(t for t in self._pending
                           if t < newest or quiet)
            batches = [(t, self._pending.pop(t)) for t in ready]
        if rescan_floor is not None:
            # a compaction replaced a WAL under us: everything at or
            # below the OLDEST retained generation's tick is gone from
            # the log. If a rescanned tail had not yet READ that far
            # (its dedup last_tick is below the truncation floor), the
            # dropped records are unrecoverable from the tail — refuse
            # to silently serve a gapped state; dying loudly lets the
            # operator (or autoscaler spawn_cb) restart the replica,
            # which re-hydrates from the newest generation and is
            # whole again.
            floor = self.driver.oldest_snapshot_tick()
            if floor is not None and rescan_floor < floor:
                raise ReplicaHydrationError(
                    f"the primary compacted its WAL past this replica's "
                    f"tail position (seen tick {rescan_floor} < oldest "
                    f"retained generation tick {floor}) — the replica "
                    f"lagged more than the snapshot retention window; "
                    f"restart it to re-hydrate from the newest "
                    f"generation")
        if not batches:
            return time_counter
        # coalesce: per-source concatenation in tick order = the summed
        # delta of every ready tick
        merged: dict[str, list] = {}
        for t, by_sid in batches:
            for sid, entries in by_sid.items():
                merged.setdefault(sid, []).extend(entries)
                self.records_applied += 1
                self.entries_applied += len(entries)
        scheduler = runtime.scheduler
        for sid in sorted(merged):
            scheduler.push_source(
                self._nodes[sid],
                Delta([(k, r, d) for k, r, d, *_o in merged[sid]]))
        scheduler.run_time(time_counter)
        runtime._last_completed_tick = time_counter
        runtime.last_tick_at = _time.monotonic()
        time_counter += 1
        self.applied_tick = batches[-1][0]
        if self.catchup_wall_s is None \
                and self.applied_tick >= self.primary_watermark:
            self.catchup_wall_s = _time.monotonic() - self._started_at
        return time_counter

    # -- failover re-anchor --------------------------------------------------
    def reanchor(self, epoch: int, tick: int) -> None:
        """Re-anchor this replica's WAL tail on a new primary's timeline
        (router broadcast after a promotion). Pending ticks past the
        promotion tick are the dead primary's incomplete final commit —
        the new primary truncated them from every log, so they are
        dropped here too and the tails rescan from byte 0 (tick-deduped,
        so only the genuinely-new epoch records apply). A replica that
        already APPLIED a tick past the promotion point served state the
        new timeline does not contain — it poisons itself and the next
        pump dies loudly (ReplicaHydrationError); a restart re-hydrates
        from the shared root and is whole again."""
        with self._lock:
            self.fleet_epoch = max(self.fleet_epoch, int(epoch))
            for t in [t for t in self._pending if t > tick]:
                del self._pending[t]
            self.primary_watermark = min(self.primary_watermark, tick)
            for tail in self._tails.values():
                # force a full rescan: the new primary's truncate_after
                # may have shrunk the file below our offset, and its
                # first append may otherwise race the shrink detection
                if hasattr(tail, "_offset"):
                    tail._ino, tail._offset = None, 0
                tail.last_tick = min(tail.last_tick, tick)
            if self.applied_tick > tick:
                self._poisoned = (
                    f"replica {self.replica_id} applied tick "
                    f"{self.applied_tick} but the fleet promoted a new "
                    f"primary at epoch {epoch} whose timeline ends at "
                    f"tick {tick} — this replica served state the new "
                    f"timeline does not contain; restart it to "
                    f"re-hydrate from the shared root")
            logger.warning(
                "replica %s re-anchored on fencing epoch %d at tick %d",
                self.replica_id, epoch, tick)

    # -- fleet surface -------------------------------------------------------
    def staleness_ticks(self) -> int:
        return max(0, self.primary_watermark - self.applied_tick)

    def stats(self) -> dict:
        return {
            "replica_id": self.replica_id,
            "applied_tick": self.applied_tick,
            "primary_watermark": self.primary_watermark,
            "staleness_ticks": self.staleness_ticks(),
            "generation": self.generation,
            "hydrate_wall_s": (None if self.hydrate_wall_s is None
                               else round(self.hydrate_wall_s, 6)),
            "catchup_wall_s": (None if self.catchup_wall_s is None
                               else round(self.catchup_wall_s, 6)),
            "records_applied": self.records_applied,
            "entries_applied": self.entries_applied,
            "tailed_sources": sorted(self._tails),
            "fleet_epoch": self.fleet_epoch,
        }

    def close(self) -> None:
        self.driver.close()


class ControlClient:
    """The replica (or read-serving primary) side of the fleet control
    channel: dials the router's control listener, authenticates with the
    shared HMAC handshake, announces ``(role, replica id, HTTP serving
    endpoint)`` and then heartbeats applied tick / staleness / serving
    quantiles every ``PATHWAY_REPLICA_HEARTBEAT_MS``. A ``("stop", ...)``
    frame from the router (scale-in) stops the runtime gracefully.
    Reconnects with backoff if the router restarts; never takes the
    serving path down with it."""

    def __init__(self, runtime, address: tuple[str, int],
                 role: str = "replica", replica_id: str | None = None):
        from pathway_tpu.internals.config import _env_int

        self.runtime = runtime
        self.address = address
        self.role = role
        self.replica_id = replica_id or replica_id_from_env()
        self.heartbeat_s = max(
            10, _env_int("PATHWAY_REPLICA_HEARTBEAT_MS", 250)) / 1000.0
        self._thread = None
        self._sock: socket.socket | None = None

    # the serving endpoint to announce: the first live webserver of the
    # runtime's rest sources (queries go THERE; the monitoring port is in
    # the heartbeat for dashboards)
    def _serving_endpoint(self) -> tuple[str, int] | None:
        for _node, _session, ds in self.runtime.sessions:
            ws = getattr(ds, "webserver", None)
            if ws is not None and ws._started.is_set() and ws.port:
                host = ws.host
                if host in ("0.0.0.0", "::"):
                    host = "127.0.0.1"
                return host, int(ws.port)
        return None

    def _heartbeat_payload(self) -> dict:
        rt = self.runtime
        # role is read LIVE off the runtime: a promotion flips
        # runtime.role replica→primary mid-run and the router learns the
        # transition from the very next heartbeat (its failover clock
        # stops on the first primary-role heartbeat)
        hb = {"replica": self.replica_id,
              "role": getattr(rt, "role", self.role),
              "at": _time.time()}
        # re-announce the serving endpoint: if the webserver was not yet
        # bound at hello time, the router learns the address from the
        # first heartbeat that carries it instead of never routing here
        endpoint = self._serving_endpoint()
        if endpoint is not None:
            hb["host"], hb["port"] = endpoint
        tailer = getattr(rt, "replica", None)
        if tailer is not None:
            hb.update(tailer.stats())
        else:
            p = getattr(rt, "persistence", None)
            if p is not None:
                hb["applied_tick"] = p.last_commit_watermark
                hb["primary_watermark"] = p.last_commit_watermark
                hb["generation"] = p.snapshot_generation
                hb["fleet_epoch"] = getattr(p, "fencing_epoch", 0)
            hb["staleness_ticks"] = 0
        # failover bookkeeping: a just-promoted primary announces the
        # tick its adopted timeline ends at, so the router can re-anchor
        # the surviving replicas exactly there (engine/router.py)
        if getattr(rt, "promotion_tick", None) is not None:
            hb["promotion_tick"] = rt.promotion_tick
            hb["promotions"] = rt.promotions
            if rt.failover_promotion_s is not None:
                hb["failover_promotion_s"] = round(
                    rt.failover_promotion_s, 6)
        tracker = getattr(rt.recorder, "requests", None) \
            if rt.recorder is not None else None
        if tracker is not None:
            qs = tracker.quantiles_ms()
            if qs is not None:
                hb["p50_ms"] = round(qs[0.5], 3)
                hb["p95_ms"] = round(qs[0.95], 3)
            hb["requests"] = tracker.count
            # replica-side SLO burn: /fleet/status shows every process's
            # burn rate next to the router's front-door one
            hb["burn_rate"] = round(tracker.burn_rate(), 4)
        qos = getattr(rt, "qos", None)
        if qos is not None:
            # QoS state rides the heartbeat (engine/qos.py): the router
            # steers load away from a shedding endpoint BEFORE its p95
            # degrades, and /fleet/status shows per-endpoint QoS
            hb["qos"] = qos.heartbeat_state()
        mon = getattr(rt, "http_server", None)
        if mon is not None:
            hb["monitoring_port"] = mon.port
        # semantic result cache: the index-version watermark rides the
        # heartbeat so the router can serve fleet-wide hits without
        # touching a primary or replica (engine/result_cache.py) — plus
        # compact cache stats for /fleet/status
        from pathway_tpu.engine.result_cache import live_cache_stats

        rc = live_cache_stats()
        if rc is not None:
            hb["index_version"] = rc["version"]
            hb["result_cache"] = {
                "entries": rc["entries"], "hits": rc["hits"],
                "misses": rc["misses"],
                "invalidations": rc["invalidations"],
                "invalidations_per_tick": rc["invalidations_per_tick"],
                "hit_ratio": round(rc["hit_ratio"], 4)}
        # monotonic<->wall clock anchor (engine/fleet_observability.py):
        # rides every heartbeat so the router can clock-align this
        # process's monotonic trace timestamps in /fleet/trace even when
        # the scraped payload lacks its own wall anchor
        from pathway_tpu.engine.fleet_observability import clock_anchor

        hb["clock"] = clock_anchor()
        return hb

    def start(self) -> None:
        self._thread = spawn(self._run, name=f"ctrl-{self.replica_id}")

    def _connect_once(self) -> socket.socket:
        from pathway_tpu.engine.multiproc import (control_authkey,
                                                  hmac_handshake,
                                                  send_control_frame)

        sock = socket.create_connection(self.address, timeout=5.0)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hmac_handshake(sock, control_authkey(),
                           _time.monotonic() + 5.0)
            # wait (bounded) for the serving endpoint: the webserver
            # starts on the reader thread, typically within milliseconds
            deadline = _time.monotonic() + 10.0
            endpoint = self._serving_endpoint()
            while endpoint is None and _time.monotonic() < deadline:
                if self.runtime._stop.wait(0.02):
                    break
                endpoint = self._serving_endpoint()
            hello = {"replica": self.replica_id, "role": self.role}
            if endpoint is not None:
                hello["host"], hello["port"] = endpoint
            send_control_frame(sock, "hello", hello)
            return sock
        except BaseException:
            sock.close()
            raise

    def _run(self) -> None:
        from pathway_tpu.engine.multiproc import (recv_control_frame,
                                                  send_control_frame)
        from pathway_tpu.internals.retries import \
            ExponentialBackoffRetryStrategy

        # shared backoff policy (internals/retries.py): full jitter so a
        # fleet of replicas re-dialing a bounced router does not stampede
        # it in lockstep; max_retries is effectively unbounded — the loop
        # itself decides when to stop (runtime._stop), the strategy only
        # shapes the delays
        retry = ExponentialBackoffRetryStrategy(
            max_retries=1_000_000, initial_delay_ms=200,
            backoff_factor=2.0, max_delay_ms=5_000, jitter=True,
            seed=hash(self.replica_id) & 0xFFFF)
        attempt = 0
        while not self.runtime._stop.is_set():
            try:
                sock = self._connect_once()
            except Exception as e:  # noqa: BLE001 — reconnect with backoff
                delay = retry.delay_for_attempt(attempt)  # seconds
                # clamp: past the max_delay cap the schedule is flat, and
                # float 2.0**attempt overflows for very long outages
                attempt = min(attempt + 1, 16)
                logger.debug("control dial to %s failed: %s; retrying "
                             "in %.2fs", self.address, e, delay)
                if self.runtime._stop.wait(delay):
                    return
                continue
            attempt = 0  # connected: the next outage backs off from scratch
            self._sock = sock
            try:
                while not self.runtime._stop.is_set():
                    send_control_frame(sock, "hb",
                                       self._heartbeat_payload())
                    # between heartbeats, watch for router commands
                    sock.settimeout(self.heartbeat_s)
                    try:
                        tag, payload = recv_control_frame(sock)
                    except socket.timeout:
                        continue
                    if tag == "stop":
                        logger.info(
                            "replica %s: router requested stop (%s) — "
                            "shutting down gracefully", self.replica_id,
                            (payload or {}).get("reason", "scale-in"))
                        self.runtime.stop()
                        return
                    if tag == "promote":
                        # hand the request to the commit loop (it runs
                        # promotion synchronously between ticks); keep
                        # this control loop alive — the router learns the
                        # outcome from role flips in later heartbeats
                        logger.warning(
                            "replica %s: router requested promotion (%s)",
                            self.replica_id, payload or {})
                        self.runtime.request_promotion(payload or {})
                        continue
                    if tag == "reanchor":
                        tailer = getattr(self.runtime, "replica", None)
                        if tailer is not None and payload:
                            tailer.reanchor(int(payload["epoch"]),
                                            int(payload["tick"]))
                        continue
            except (OSError, EOFError) as e:
                logger.debug("control link to router lost (%s); "
                             "redialing", e)
            finally:
                self._sock = None
                try:
                    sock.close()
                except OSError:
                    pass

    def stop(self) -> None:
        # the thread observes runtime._stop; closing the socket unblocks
        # a recv in flight
        sock = self._sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2.0)


def control_address_from_env() -> tuple[str, int] | None:
    """``PATHWAY_ROUTER_CONTROL=host:port`` — where this process's
    control client should register (None = no router)."""
    raw = os.environ.get("PATHWAY_ROUTER_CONTROL", "").strip()
    if not raw:
        return None
    host, _sep, port = raw.rpartition(":")
    try:
        return (host or "127.0.0.1", int(port))
    except ValueError:
        logger.warning("unparseable PATHWAY_ROUTER_CONTROL=%r ignored",
                       raw)
        return None
