"""Steady-state device-discipline sanitizer (PWT4xx's runtime twin).

The PWT4xx static pass (internals/static_check/perf_check.py) proves
properties of the *source*: no unbucketed dispatch, no hidden sync, no
implicit per-tick transfer. This module asserts the same contract about
the *execution*: once ``pw.warmup()`` has walked the bucket ladder and
declared **steady state**, a serving process must never compile another
XLA executable and never transfer host memory to the device implicitly —
either one is a silent latency cliff the static pass missed (a dynamic
dispatch the AST could not resolve, an unpinned batch dimension, a numpy
operand snuck in through a config path).

Mirrors ``engine/locking.py``'s env-armed pattern — zero overhead off:

- Default: nothing is registered, nothing is wrapped; every helper here
  is a cheap no-op behind one env check.
- ``PATHWAY_DEVICE_SANITIZER=1``: :func:`arm` (called by ``pw.warmup``)
  registers a JAX compile-event listener
  (``/jax/core/compile/backend_compile_duration`` — fires once per
  actual backend compile, never on cache hits). Compiles during the
  warmup window are counted as warmup. After
  :func:`declare_steady_state` (``pw.warmup`` calls it on completion)
  any further compile raises :class:`DeviceDisciplineViolation` naming
  the in-flight operator, tick, and user frame (via the flight
  recorder's live in-flight marker), and JAX's transfer guard is set to
  ``disallow`` so an implicit host→device operand transfer raises at
  the offending dispatch (explicit ``device_put`` / ``jnp.asarray``
  residency establishment stays legal — that is the fix, not the bug).
- ``PATHWAY_DEVICE_SANITIZER=report``: violations are recorded
  (:func:`violations`) and logged, never raised; the transfer guard
  uses ``log`` (C++ stderr lines) instead of ``disallow``.

Maintenance windows — slab growth, recovery, re-warming — are legal
compile sites: wrap them in :func:`suspend_steady_state`, which lifts
the guard for the block and restores it after. ``pw.warmup`` itself
suspends while it walks the ladder, so re-warming an armed process
counts as warmup, not violation.

Benches count compiles with the sanitizer OFF through
:func:`install_compile_counter`, which registers the same listener
purely as a counter (no env gate, no guard) — bench.py's per-leg
compile-count columns ride on it.
"""

from __future__ import annotations

import contextlib
import logging
import os

from pathway_tpu.engine.locking import create_lock

logger = logging.getLogger(__name__)

__all__ = [
    "DeviceDisciplineViolation", "arm", "declare_steady_state",
    "in_steady_state", "install_compile_counter", "post_warmup_compiles",
    "sanitizer_enabled", "suspend_steady_state", "violations",
    "warmup_compiles",
]

#: the JAX monitoring event that fires once per actual backend compile
#: (cache hits — persistent or in-process — never emit it)
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def sanitizer_enabled() -> bool:
    """Truthy ``PATHWAY_DEVICE_SANITIZER`` arms the sanitizer. Checked at
    arm/declare time — a run toggles by env, and the disabled path stays
    a no-op behind this one check."""
    return os.environ.get("PATHWAY_DEVICE_SANITIZER", "").strip().lower() \
        in ("1", "true", "on", "yes", "report", "warn")


def _raise_on_violation() -> bool:
    return os.environ.get("PATHWAY_DEVICE_SANITIZER", "").strip().lower() \
        not in ("report", "warn")


class DeviceDisciplineViolation(RuntimeError):
    """A post-warmup XLA compile (or implicit transfer) landed inside the
    steady-state serving window — a latency cliff on a live tick that
    warmup was supposed to have eliminated."""


class _SanitizerState:
    """Process-wide bookkeeping. One instance per process; tests swap in
    a fresh one via :func:`_reset_for_tests` (the JAX listener is
    registered once per process and reads whatever state is current)."""

    def __init__(self):
        self.mutex = create_lock("device_sanitizer.state")
        self.armed = False
        self.steady = False
        self.warmup_compiles = 0
        self.post_warmup_compiles = 0
        self.total_compiles = 0
        self.violation_log: list[dict] = []


_STATE = _SanitizerState()
# jax.monitoring offers no unregistration, so the listener is installed
# at most once per process and consults the live _STATE on every event
_LISTENER_INSTALLED = False


def _reset_for_tests() -> None:
    """Fresh counters/flags (unit tests only). Also drops any leftover
    transfer guard so one test's steady state cannot poison the next."""
    global _STATE
    _STATE = _SanitizerState()
    _set_transfer_guard("allow")


def _inflight_context() -> str:
    """``operator=... tick=... at <user frame>`` from the flight
    recorder's live in-flight marker, or a stub when nothing records."""
    try:
        from pathway_tpu.engine.flight_recorder import live_inflight

        info = live_inflight()
    except Exception:
        info = None
    if not info:
        return "no operator in flight (dispatch outside the engine loop?)"
    return (f"operator {info.get('operator')!r} "
            f"(class {info.get('op_class')}) tick={info.get('tick')} "
            f"at {info.get('user_frame')}")


def _record_violation(kind: str, message: str) -> None:
    with _STATE.mutex:
        _STATE.violation_log.append({"kind": kind, "message": message})
    if _raise_on_violation():
        raise DeviceDisciplineViolation(message)
    logger.error("device sanitizer: %s", message)


def violations() -> list[dict]:
    """Violations recorded so far (raise mode records before raising, so
    post-mortems and tests can read the full list either way)."""
    with _STATE.mutex:
        return list(_STATE.violation_log)


def warmup_compiles() -> int:
    """Backend compiles observed while armed but before steady state —
    the warmup window's legitimate ladder walk."""
    return _STATE.warmup_compiles


def post_warmup_compiles() -> int:
    """Backend compiles observed after :func:`declare_steady_state` —
    the number the serving canary gates at zero."""
    return _STATE.post_warmup_compiles


def in_steady_state() -> bool:
    return _STATE.steady


def _on_compile_event(event: str, duration: float, **_kw) -> None:
    """The one listener, installed once per process. Raising from here
    propagates to the dispatching call site (verified: the jit cache is
    unaffected and the next dispatch retries cleanly), which is exactly
    where the violation belongs."""
    if event != _COMPILE_EVENT:
        return
    with _STATE.mutex:
        _STATE.total_compiles += 1
        if not _STATE.armed:
            return
        if not _STATE.steady:
            _STATE.warmup_compiles += 1
            return
        _STATE.post_warmup_compiles += 1
    _record_violation(
        "post-warmup-compile",
        f"XLA backend compile ({duration * 1e3:.0f} ms) inside the "
        f"steady-state serving window: {_inflight_context()} — an "
        f"unwarmed shape reached a jitted kernel; bucket the dispatch "
        f"or extend pw.warmup's ladder (wrap legitimate maintenance "
        f"compiles in device_sanitizer.suspend_steady_state())")


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    import jax

    jax.monitoring.register_event_duration_secs_listener(
        _on_compile_event)
    _LISTENER_INSTALLED = True


def install_compile_counter():
    """Register the compile listener purely as a counter (no env gate,
    no guard, nothing ever raises) and return a zero-arg callable
    yielding the process-lifetime backend-compile count. bench.py's
    per-leg compile columns diff it around each leg."""
    _install_listener()
    return lambda: _STATE.total_compiles


def _set_transfer_guard(mode: str) -> None:
    try:
        import jax

        jax.config.update("jax_transfer_guard_host_to_device", mode)
    except Exception:
        # pre-guard jax: compile discipline still enforced, transfers not
        logger.debug("transfer guard unavailable", exc_info=True)


def arm() -> bool:
    """Install the compile listener and open the warmup window (compiles
    count as warmup until :func:`declare_steady_state`). Idempotent;
    no-op (returns False) unless ``PATHWAY_DEVICE_SANITIZER`` is set.
    ``pw.warmup`` calls this on entry."""
    if not sanitizer_enabled():
        return False
    _install_listener()
    with _STATE.mutex:
        _STATE.armed = True
        _STATE.steady = False
    _set_transfer_guard("allow")
    return True


def declare_steady_state() -> bool:
    """Close the warmup window: from here on, any backend compile is a
    violation and implicit host→device transfers are guarded
    (``disallow`` in raise mode, ``log`` in report mode). ``pw.warmup``
    calls this on completion; idempotent; no-op unless armed."""
    if not sanitizer_enabled():
        return False
    _install_listener()
    with _STATE.mutex:
        _STATE.armed = True
        _STATE.steady = True
    _set_transfer_guard("disallow" if _raise_on_violation() else "log")
    return True


@contextlib.contextmanager
def suspend_steady_state(why: str = ""):
    """Temporarily lift steady state for a legitimate maintenance window
    (slab growth, recovery, re-warming): compiles inside the block count
    as warmup, the transfer guard is dropped, and the previous state is
    restored on exit. Free when the sanitizer is off."""
    if not _STATE.steady:
        yield
        return
    logger.info("device sanitizer: steady state suspended%s",
                f" ({why})" if why else "")
    with _STATE.mutex:
        _STATE.steady = False
    _set_transfer_guard("allow")
    try:
        yield
    finally:
        declare_steady_state()
