"""Per-partition offset antichains for partitioned sources.

Rebuild of the reference's ``OffsetAntichain``
(src/persistence/frontier.rs:12): per source, the frontier of durable
progress is a map ``partition -> highest contiguous offset``. Partitioned
readers (Kafka topic-partitions, sharded logs) label every pushed entry
with ``offset=("part", partition, offset)``; the persistence layer folds
those labels into an antichain, stores it with each commit, and on resume
hands it to the source's ``seek_offsets(antichain)`` so the reader
continues each partition exactly past its durable prefix — no prefix
replay assumption, robust to cross-partition interleaving.
"""

from __future__ import annotations

from typing import Any, Iterable


class OffsetAntichain:
    """partition -> max offset seen; the durable frontier of one source."""

    __slots__ = ("offsets",)

    def __init__(self, offsets: dict | None = None):
        self.offsets: dict[Any, Any] = dict(offsets or {})

    def advance(self, partition: Any, offset: Any) -> None:
        cur = self.offsets.get(partition)
        if cur is None or offset > cur:
            self.offsets[partition] = offset

    def advance_from_entry_offset(self, entry_offset: Any) -> bool:
        """Fold one entry's offset label; returns whether it was
        partition-shaped (("part", partition, offset))."""
        if (isinstance(entry_offset, tuple) and len(entry_offset) == 3
                and entry_offset[0] == "part"):
            self.advance(entry_offset[1], entry_offset[2])
            return True
        return False

    def merge(self, other: "OffsetAntichain") -> "OffsetAntichain":
        """Frontier union — max per partition (reference: merging worker
        frontiers on load, persistence/state.rs:120-226)."""
        out = OffsetAntichain(self.offsets)
        for p, o in other.offsets.items():
            out.advance(p, o)
        return out

    def pop(self, partition: Any, default: Any = None) -> Any:
        """Drop one partition from the frontier (offset-out-of-range
        recovery re-resolves just that partition via auto.offset.reset)."""
        return self.offsets.pop(partition, default)

    def get(self, partition: Any, default: Any = None) -> Any:
        return self.offsets.get(partition, default)

    def is_past(self, partition: Any, offset: Any) -> bool:
        """Is ``offset`` already covered by the durable frontier?"""
        cur = self.offsets.get(partition)
        return cur is not None and offset <= cur

    def __bool__(self) -> bool:
        return bool(self.offsets)

    def __eq__(self, other) -> bool:
        return isinstance(other, OffsetAntichain) \
            and self.offsets == other.offsets

    def __repr__(self) -> str:
        return f"OffsetAntichain({self.offsets!r})"

    def to_dict(self) -> dict:
        return dict(self.offsets)

    @classmethod
    def from_entries(cls, offsets: Iterable[Any]) -> "OffsetAntichain":
        out = cls()
        for off in offsets:
            out.advance_from_entry_offset(off)
        return out
