"""Checkpoint/resume driver for the streaming runtime.

Rebuild of the reference's persistence stack (src/persistence/ —
``WorkerPersistentStorage`` tracker.rs:20, ``MetadataAccessor`` state.rs:20,
snapshot record/replay in src/connectors/snapshot.rs + mod.rs:215-368):
each source's parsed entries are appended to a durable **snapshot log**
together with the commit timestamp; on restart the driver replays every
logged entry into the source's session (state is rebuilt by re-running the
dataflow over the replayed prefix) and suppresses the first N live entries
the re-started reader emits, N being the number durably logged — the
"rewind then continue from stored offsets" protocol of the reference,
expressed as replay+skip so *any* deterministic reader gets exactly-once
input without a per-reader seek API.

The log is authoritative (no separate metadata file to keep consistent):
records are length-prefixed, CRC32-checksummed pickles decoded by a
RESTRICTED unpickler (class whitelist below — a snapshot written by an
attacker with access to shared storage must not execute code on resume),
fsynced per commit; a truncated or corrupted tail record (crash
mid-append, bit rot) is detected and dropped on load. This mirrors the
reference's rule that only data finalized at the last *committed* frontier
is recovered (state.rs:120-226) — the reference gets decode safety for
free from serde/bincode's data-only model; the Python build has to
enforce it explicitly.

Backends: ``filesystem`` (a directory of per-source logs) and ``mock``
(in-memory, state kept on the Backend object — the test double, like the
reference's mock metadata backend).
"""

from __future__ import annotations

import io
import os
import pickle
import struct
import zlib
from typing import Any

from pathway_tpu.testing import faults

_HDR = struct.Struct("<QI")  # payload length, CRC32(payload)
_MAGIC = b"PWSNAP01"  # format marker; bump the digit on layout changes

# Decode whitelist: data classes that legitimately appear inside logged
# (time, [(key, row, diff, offset), ...]) records — engine Values
# (internals/keys.Pointer, internals/json.Json, numpy arrays, datetimes)
# and plain containers. Anything else (os.system, builtins.eval,
# functools.partial, ...) is refused at load time.
_SAFE_GLOBALS = {
    ("builtins", n) for n in
    ("list", "tuple", "dict", "set", "frozenset", "bytearray", "complex")
} | {
    ("pathway_tpu.internals.keys", "Pointer"),
    ("pathway_tpu.internals.json", "Json"),
    ("datetime", "datetime"), ("datetime", "date"), ("datetime", "time"),
    ("datetime", "timedelta"), ("datetime", "timezone"),
    # the build's canonical datetime/duration value types host-side are
    # pandas Timestamp/Timedelta (internals/expressions/date_time.py)
    ("pandas._libs.tslibs.timestamps", "_unpickle_timestamp"),
    ("pandas._libs.tslibs.timestamps", "Timestamp"),
    ("pandas._libs.tslibs.timedeltas", "_timedelta_unpickle"),
    ("pandas._libs.tslibs.timedeltas", "Timedelta"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"snapshot log references forbidden global {module}.{name} — "
            "refusing to decode (possible tampering)")


def _safe_loads(payload: bytes):
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


class SnapshotLog:
    """Append-only framed, checksummed, restricted-pickle log of
    (time, entries) records."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = None

    def _scan(self) -> tuple[list[tuple[int, list]], int]:
        """(intact records, byte offset of the end of the last intact one).
        A torn tail record — crash mid-append — is excluded from both."""
        records: list = []
        if not os.path.exists(self.path):
            return records, 0
        with open(self.path, "rb") as f:
            data = f.read()
        if not data:
            return records, 0
        if len(data) < len(_MAGIC) and _MAGIC.startswith(data):
            # crash during the very first append, mid-magic: an empty log
            # with a torn tail, not an alien file
            return records, 0
        if not data.startswith(_MAGIC):
            # refuse to guess: silently reading an alien/older layout as
            # empty would wipe it on the next append
            raise ValueError(
                f"{self.path}: not a {_MAGIC.decode()} snapshot log — "
                "refusing to read or overwrite it")
        pos = len(_MAGIC)
        while pos + _HDR.size <= len(data):
            length, crc = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + length
            if end > len(data):
                break
            payload = data[pos + _HDR.size:end]
            if zlib.crc32(payload) != crc:
                break  # torn/corrupt tail — recover the prefix before it
            try:
                rec = _safe_loads(payload)
            except pickle.UnpicklingError:
                raise  # forbidden global = tampering, not a torn tail
            except Exception:
                break
            records.append(rec)
            pos = end
        return records, pos

    def read_all(self) -> list[tuple[int, list]]:
        return self._scan()[0]

    def append(self, time: int, entries: list) -> None:
        if self._f is None:
            # truncate any torn tail record before appending, or every later
            # record would sit behind unreadable bytes forever
            _records, valid = self._scan()
            self._f = open(self.path, "ab")
            if self._f.tell() != valid:
                self._f.truncate(valid)
                self._f.seek(valid)
            if valid == 0:
                self._f.write(_MAGIC)
        payload = pickle.dumps((time, entries), protocol=pickle.HIGHEST_PROTOCOL)
        faults.hit("persistence.append", path=self.path, time=time)
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        # fault point between header and payload: an armed action aborts
        # here leaving exactly the torn-tail record _scan must drop
        faults.hit("persistence.append.torn", path=self.path, time=time)
        self._f.write(payload)
        self._f.flush()
        faults.hit("persistence.fsync", path=self.path, time=time)
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class S3SnapshotLog:
    """Object-per-commit snapshot log on S3-compatible storage: each
    append PUTs ``<prefix>/streams/<sid>/<seq:016d>`` containing one
    framed, checksummed record; restore lists the prefix and replays
    objects in key order. Object stores give atomic whole-object PUTs, so
    the torn-tail handling of the file log becomes 'skip a corrupt
    object' (reference: S3 metadata/stream backends,
    src/persistence/metadata_backends/ + connectors/snapshot.rs)."""

    def __init__(self, client, root_prefix: str, source_id: str):
        self.client = client
        self.prefix = "/".join(
            p for p in (root_prefix.strip("/"), "streams", source_id) if p)
        self._seq: int | None = None

    def read_all(self) -> list[tuple[int, list]]:
        """Contiguous durable prefix, stopping at the first gap or corrupt
        object — exactly SnapshotLog._scan's torn-tail rule. Skipping a
        hole would desynchronize the replay+skip resume protocol (the
        skip counter assumes the replayed records are a PREFIX of what
        the reader re-emits)."""
        records: list = []
        expect = 0
        for obj in sorted(self.client.list_objects(self.prefix + "/"),
                          key=lambda o: o["key"]):
            try:
                seq = int(obj["key"].rsplit("/", 1)[-1])
            except ValueError:
                continue  # foreign object under the prefix
            if seq != expect:
                break  # gap: a later commit without its predecessor
            data = self.client.get_object(obj["key"])
            if not data.startswith(_MAGIC) \
                    or len(data) < len(_MAGIC) + _HDR.size:
                break
            length, crc = _HDR.unpack_from(data, len(_MAGIC))
            payload = data[len(_MAGIC) + _HDR.size:
                           len(_MAGIC) + _HDR.size + length]
            if len(payload) != length or zlib.crc32(payload) != crc:
                break  # interrupted upload: prefix ends here
            records.append(_safe_loads(payload))
            expect += 1
        self._seq = expect  # next append overwrites a torn slot
        return records

    def _next_seq(self) -> int:
        """Key listing only — no GETs/unpickling just to number an append
        (the records themselves are read once by the driver's cache).
        Appends after the CONTIGUOUS prefix: a torn/corrupt object's slot
        gets overwritten, matching read_all's prefix rule."""
        keys = set()
        for obj in self.client.list_objects(self.prefix + "/"):
            try:
                keys.add(int(obj["key"].rsplit("/", 1)[-1]))
            except ValueError:
                pass
        seq = 0
        while seq in keys:
            seq += 1
        return seq

    def append(self, time: int, entries: list) -> None:
        if self._seq is None:
            self._seq = self._next_seq()
        payload = pickle.dumps((time, entries),
                               protocol=pickle.HIGHEST_PROTOCOL)
        body = _MAGIC + _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        self.client.put_object(f"{self.prefix}/{self._seq:016d}", body)
        self._seq += 1

    def close(self) -> None:
        pass


class MockLog:
    """In-memory log living on the Backend object, surviving re-runs that
    reuse the same ``pw.persistence.Backend.mock()`` instance."""

    def __init__(self, store: dict, source_id: str):
        self._records = store.setdefault(source_id, [])

    def read_all(self) -> list[tuple[int, list]]:
        return list(self._records)

    def append(self, time: int, entries: list) -> None:
        self._records.append((time, entries))

    def close(self) -> None:
        pass


class _RecordingSession:
    """Session proxy for a restarted source: buffers live entries (with
    their source offsets) for durable append at the next commit. For
    non-seekable sources it additionally drops the first ``skip`` live
    entries — those were replayed from the snapshot log (the reference's
    offset-continuation, expressed as replay+skip). Duck-types
    io._datasource.Session (push/drain/close/closed)."""

    def __init__(self, inner, skip: int):
        self._inner = inner
        self._skip = skip
        self.pending: list = []  # (key, row, diff, offset)
        self.closed = inner.closed
        self.stopping = inner.stopping

    @property
    def stop_requested(self) -> bool:
        return self.stopping.is_set()

    def sleep(self, seconds: float) -> bool:
        return self._inner.sleep(seconds)

    def push(self, key, row, diff: int = 1, offset=None) -> None:
        if self._skip > 0:
            self._skip -= 1
            return
        self.pending.append((key, row, diff, offset))
        self._inner.push(key, row, diff)

    def drain(self) -> list:
        return self._inner.drain()

    def close(self) -> None:
        self._inner.close()


class PersistenceDriver:
    """Engine side of ``pw.persistence.Config`` (python half at
    pathway_tpu/persistence/__init__.py; reference equivalent
    persistence/__init__.py:12,89 + src/persistence/tracker.rs)."""

    def __init__(self, config):
        self.config = config
        backend = config.backend
        self.kind = backend.kind
        self._s3 = None
        if self.kind == "s3":
            # native SigV4 client (io/s3/_client.py): snapshots become
            # objects under <bucket>/<prefix>/streams/<sid>/<seq>
            from pathway_tpu.io.s3._client import (S3Client,
                                                   client_from_settings,
                                                   split_bucket_prefix)

            settings = backend.options.get("bucket_settings")
            bucket, prefix = split_bucket_prefix(
                backend.path or "",
                getattr(settings, "bucket_name", None) if settings else None)
            if settings is not None:
                self._s3 = client_from_settings(settings, bucket=bucket)
            else:
                self._s3 = S3Client(bucket=bucket)  # env credential chain
            self.root = prefix
        elif self.kind == "azure":
            # Azure Blob via the in-repo SharedKey/SAS client; blob surface
            # duck-types S3Client so the object-per-commit log is shared
            from pathway_tpu.io.azure_blob import client_from_backend

            self._s3, self.root = client_from_backend(backend)
        elif self.kind == "filesystem":
            self.root = backend.path
            os.makedirs(os.path.join(self.root, "streams"), exist_ok=True)
        elif self.kind == "mock":
            if not hasattr(backend, "_mock_store"):
                backend._mock_store = {}
            self.root = None
        else:
            raise ValueError(f"unknown persistence backend {self.kind!r}")
        self._backend = backend
        self._sessions: list[tuple[str, Any, Any]] = []  # (sid, log, rec_session)
        self._restore_time: int | None = None
        self._record_cache: dict[str, list] = {}  # sid → records (read once)
        self._attached_ids: set[str] = set()

    # -- identity ----------------------------------------------------------
    def _source_id(self, datasource) -> str:
        pid = getattr(datasource, "persistent_id", None)
        if pid:
            return str(pid)
        # `_uid` is a process-wide construction counter: stable only if the
        # program builds the same sources in the same order every run.
        import logging

        logging.getLogger(__name__).warning(
            "source %r has no persistent_id; falling back to construction "
            "order (%s-%s) — adding/reordering sources between runs will "
            "mismatch snapshot logs. Pass persistent_id= to the connector.",
            datasource.name, datasource.name, datasource._uid)
        return f"{datasource.name}-{datasource._uid}"

    def _log_for(self, source_id: str):
        if self.kind == "mock":
            return MockLog(self._backend._mock_store, source_id)
        if self._s3 is not None:
            return S3SnapshotLog(self._s3, self.root, source_id)
        return SnapshotLog(os.path.join(self.root, "streams",
                                        source_id + ".snap"))

    # -- runtime API (called by StreamingRuntime) --------------------------
    def _records(self, sid: str) -> list:
        """Read (and cache) a source's log records — restore_time and
        attach_source both need them; unpickle only once per startup."""
        if sid not in self._record_cache:
            self._record_cache[sid] = self._log_for(sid).read_all()
        return self._record_cache[sid]

    def restore_time(self) -> int:
        """Last committed logical time across all logged sources (0 = fresh)."""
        if self._restore_time is not None:
            return self._restore_time
        last = 0
        if self.kind == "mock":
            sids = list(self._backend._mock_store.keys())
        elif self._s3 is not None:
            prefix = "/".join(p for p in (self.root.strip("/"), "streams")
                              if p) + "/"
            sids = sorted({
                obj["key"][len(prefix):].split("/", 1)[0]
                for obj in self._s3.list_objects(prefix)})
        else:
            streams = os.path.join(self.root, "streams")
            sids = [f[:-5] for f in os.listdir(streams)
                    if f.endswith(".snap")] if os.path.isdir(streams) else []
        for sid in sids:
            for t, _ in self._records(sid):
                last = max(last, t)
        self._restore_time = last
        return last

    def attach_source(self, datasource, session):
        """Replay this source's durable prefix into ``session`` and return
        the recording proxy the live reader thread must push into.

        Two continuation protocols (reference: connectors/mod.rs:215-368 —
        ``rewind_from_disk_snapshot`` then continue from stored offsets):

        - **seekable** sources (define ``seek(replayed_entries)``) receive
          every replayed ``(key, row, diff, offset)`` and position their
          reader past the durable prefix themselves; nothing live is
          dropped. This is exact under reordering and file mutation.
        - otherwise the source is assumed to re-emit the identical entry
          sequence on restart, and the first N live pushes are dropped.
        """
        sid = self._source_id(datasource)
        if sid in self._attached_ids:
            raise ValueError(
                f"two persisted sources share the id {sid!r} — their snapshot "
                "logs would cross-replay into each other's tables. Give each "
                "connector a unique persistent_id.")
        self._attached_ids.add(sid)
        log = self._log_for(sid)
        replayed: list = []
        for _t, entries in self._records(sid):
            for entry in entries:
                key, row, diff = entry[0], entry[1], entry[2]
                offset = entry[3] if len(entry) > 3 else None
                session.push(key, row, diff)
                replayed.append((key, row, diff, offset))
        from pathway_tpu.engine.offsets import OffsetAntichain

        antichain = OffsetAntichain.from_entries(
            off for _k, _r, _d, off in replayed)
        if antichain and hasattr(datasource, "seek_offsets"):
            # partitioned source: continue each partition past its durable
            # frontier (reference OffsetAntichain, persistence/frontier.rs)
            datasource.seek_offsets(antichain)
            skip = 0
        elif hasattr(datasource, "seek"):
            datasource.seek(replayed)
            skip = 0
        else:
            if replayed:
                import logging

                logging.getLogger(__name__).warning(
                    "resuming source %r with the prefix-replay protocol: the "
                    "reader is assumed to re-emit the identical first %d "
                    "entries on restart. Sources that re-read *current* "
                    "state (databases, compacted topics) need a seek() "
                    "implementation for exact resume.", sid, len(replayed))
            skip = len(replayed)
        rec = _RecordingSession(session, skip=skip)
        self._sessions.append((sid, log, rec))
        return rec

    def commit(self, time: int) -> None:
        """Durably record everything pushed since the previous commit.
        Called by the runtime after the scheduler finished time ``time``, so
        a log record's presence implies its time was fully processed."""
        for sid, log, rec in self._sessions:
            if rec.pending:
                entries, rec.pending = rec.pending, []
                log.append(time, entries)

    def close(self) -> None:
        for _sid, log, _rec in self._sessions:
            log.close()
