"""Checkpoint/resume driver for the streaming runtime.

Rebuild of the reference's persistence stack (src/persistence/ —
``WorkerPersistentStorage`` tracker.rs:20, ``MetadataAccessor`` state.rs:20,
snapshot record/replay in src/connectors/snapshot.rs + mod.rs:215-368):
each source's parsed entries are appended to a durable **snapshot log**
together with the commit timestamp; on restart the driver replays every
logged entry into the source's session (state is rebuilt by re-running the
dataflow over the replayed prefix) and suppresses the first N live entries
the re-started reader emits, N being the number durably logged — the
"rewind then continue from stored offsets" protocol of the reference,
expressed as replay+skip so *any* deterministic reader gets exactly-once
input without a per-reader seek API.

The log is authoritative (no separate metadata file to keep consistent):
records are length-prefixed, CRC32-checksummed pickles decoded by a
RESTRICTED unpickler (class whitelist below — a snapshot written by an
attacker with access to shared storage must not execute code on resume),
fsynced per commit; a truncated or corrupted tail record (crash
mid-append, bit rot) is detected and dropped on load. This mirrors the
reference's rule that only data finalized at the last *committed* frontier
is recovered (state.rs:120-226) — the reference gets decode safety for
free from serde/bincode's data-only model; the Python build has to
enforce it explicitly.

Backends: ``filesystem`` (a directory of per-source logs) and ``mock``
(in-memory, state kept on the Backend object — the test double, like the
reference's mock metadata backend).
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import struct
import time as _time
import zlib
from typing import Any, Callable

from pathway_tpu.engine.locking import blocking_call, create_lock

from pathway_tpu.testing import faults

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<QI")  # payload length, CRC32(payload)
_MAGIC = b"PWSNAP01"  # format marker; bump the digit on layout changes

# Decode whitelist: data classes that legitimately appear inside logged
# (time, [(key, row, diff, offset), ...]) records — engine Values
# (internals/keys.Pointer, internals/json.Json, numpy arrays, datetimes)
# and plain containers. Anything else (os.system, builtins.eval,
# functools.partial, ...) is refused at load time.
_SAFE_GLOBALS = {
    ("builtins", n) for n in
    ("list", "tuple", "dict", "set", "frozenset", "bytearray", "complex")
} | {
    ("pathway_tpu.internals.keys", "Pointer"),
    ("pathway_tpu.internals.json", "Json"),
    ("datetime", "datetime"), ("datetime", "date"), ("datetime", "time"),
    ("datetime", "timedelta"), ("datetime", "timezone"),
    # the build's canonical datetime/duration value types host-side are
    # pandas Timestamp/Timedelta (internals/expressions/date_time.py)
    ("pandas._libs.tslibs.timestamps", "_unpickle_timestamp"),
    ("pandas._libs.tslibs.timestamps", "Timestamp"),
    ("pandas._libs.tslibs.timedeltas", "_timedelta_unpickle"),
    ("pandas._libs.tslibs.timedeltas", "Timedelta"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"snapshot log references forbidden global {module}.{name} — "
            "refusing to decode (possible tampering)")


def _safe_loads(payload: bytes):
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


# ---------------------------------------------------------------------------
# transient-write retries (shared by the file and object-store logs)
# ---------------------------------------------------------------------------

# process-wide retry counter, exported on /metrics as
# ``pathway_tpu_persistence_write_retries`` (Prometheus counters are
# process-scoped by convention — several drivers in one process share it)
_retry_lock = create_lock("persistence._retry_lock")
_write_retries_total = 0


def write_retries_total() -> int:
    with _retry_lock:
        return _write_retries_total


def _retrying_write(body: Callable[[], None], what: str) -> None:
    """Run one durable write (append+fsync, object PUT), retrying
    transient failures with the shared exponential backoff + full jitter
    schedule (internals/retries.py). ``body`` must be safe to re-run from
    scratch: the file log truncates its torn tail before every attempt
    and object PUTs are atomic whole-object writes. Exhausting
    ``PATHWAY_PERSISTENCE_WRITE_RETRIES`` (default 3; 0 disables
    retries) re-raises the last error — the streaming commit loop then
    escalates it per ``terminate_on_error``."""
    from pathway_tpu.internals.config import _env_int

    global _write_retries_total
    budget = max(0, _env_int("PATHWAY_PERSISTENCE_WRITE_RETRIES", 3))
    strategy = None
    attempt = 0
    while True:
        try:
            body()
            return
        except Exception as e:
            if attempt >= budget:
                raise
            if strategy is None:
                from pathway_tpu.internals.retries import \
                    ExponentialBackoffRetryStrategy

                strategy = ExponentialBackoffRetryStrategy(
                    initial_delay_ms=max(1, _env_int(
                        "PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", 50)),
                    backoff_factor=2.0,
                    max_delay_ms=max(1, _env_int(
                        "PATHWAY_PERSISTENCE_RETRY_MAX_MS", 2000)),
                    jitter=True)
            delay = strategy.delay_for_attempt(attempt)
            with _retry_lock:
                _write_retries_total += 1
            logger.warning(
                "transient persistence write failure (%s): %s: %s — "
                "retry %d/%d in %.3fs", what, type(e).__name__, e,
                attempt + 1, budget, delay)
            _time.sleep(delay)
            attempt += 1


class _WaitHistogram:
    """Fixed-bucket commit-wait histogram, Prometheus-exposed as
    ``pathway_tpu_commit_wait_ms`` — how long each durable commit (append
    + fsync/PUT incl. retries) held the loop."""

    BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  1000.0)

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS_MS) + 1)
        self.sum_ms = 0.0
        self.count = 0

    def observe(self, ms: float) -> None:
        i = 0
        for b in self.BUCKETS_MS:
            if ms <= b:
                break
            i += 1
        self.counts[i] += 1
        self.sum_ms += ms
        self.count += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative count)], +Inf last (exposition format)."""
        out: list[tuple[float, int]] = []
        cum = 0
        for b, c in zip(self.BUCKETS_MS, self.counts):
            cum += c
            out.append((b, cum))
        out.append((float("inf"), cum + self.counts[-1]))
        return out


class SnapshotLog:
    """Append-only framed, checksummed, restricted-pickle log of
    (time, entries) records."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = None

    def _scan(self) -> tuple[list[tuple[int, list]], int]:
        """(intact records, byte offset of the end of the last intact one).
        A torn tail record — crash mid-append — is excluded from both."""
        records: list = []
        if not os.path.exists(self.path):
            return records, 0
        with open(self.path, "rb") as f:
            data = f.read()
        if not data:
            return records, 0
        if len(data) < len(_MAGIC) and _MAGIC.startswith(data):
            # crash during the very first append, mid-magic: an empty log
            # with a torn tail, not an alien file
            return records, 0
        if not data.startswith(_MAGIC):
            # refuse to guess: silently reading an alien/older layout as
            # empty would wipe it on the next append
            raise ValueError(
                f"{self.path}: not a {_MAGIC.decode()} snapshot log — "
                "refusing to read or overwrite it")
        pos = len(_MAGIC)
        while pos + _HDR.size <= len(data):
            length, crc = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + length
            if end > len(data):
                break
            payload = data[pos + _HDR.size:end]
            if zlib.crc32(payload) != crc:
                break  # torn/corrupt tail — recover the prefix before it
            try:
                rec = _safe_loads(payload)
            except pickle.UnpicklingError:
                raise  # forbidden global = tampering, not a torn tail
            except Exception:
                break
            records.append(rec)
            pos = end
        return records, pos

    def read_all(self) -> list[tuple[int, list]]:
        return self._scan()[0]

    def append(self, time: int, entries: list) -> None:
        if self._f is None:
            # truncate any torn tail record before appending, or every later
            # record would sit behind unreadable bytes forever
            _records, valid = self._scan()
            self._f = open(self.path, "ab")
            if self._f.tell() != valid:
                self._f.truncate(valid)
                self._f.seek(valid)
            if valid == 0:
                self._f.write(_MAGIC)
        payload = pickle.dumps((time, entries), protocol=pickle.HIGHEST_PROTOCOL)
        start = self._f.tell()

        def _write() -> None:
            # re-entry after a failed attempt: truncate whatever the torn
            # attempt left (a header without its payload) before
            # rewriting, or every later record would sit behind
            # unreadable bytes. First attempt: size == start, a no-op.
            # The file is opened in append mode, so writes land at the
            # (possibly truncated-back) end regardless of seek position.
            self._f.truncate(start)
            self._f.seek(start)
            faults.hit("persistence.append", path=self.path, time=time)
            self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
            # fault point between header and payload: an armed action
            # aborts here leaving exactly the torn-tail record _scan
            # must drop
            faults.hit("persistence.append.torn", path=self.path, time=time)
            self._f.write(payload)
            self._f.flush()
            faults.hit("persistence.fsync", path=self.path, time=time)
            # fsync is a known-blocking call: the sanitizer asserts no
            # engine lock is held while the durability write stalls
            with blocking_call("persistence.fsync"):
                os.fsync(self._f.fileno())

        _retrying_write(_write, f"append to {self.path}")

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class S3SnapshotLog:
    """Object-per-commit snapshot log on S3-compatible storage: each
    append PUTs ``<prefix>/streams/<sid>/<seq:016d>`` containing one
    framed, checksummed record; restore lists the prefix and replays
    objects in key order. Object stores give atomic whole-object PUTs, so
    the torn-tail handling of the file log becomes 'skip a corrupt
    object' (reference: S3 metadata/stream backends,
    src/persistence/metadata_backends/ + connectors/snapshot.rs)."""

    def __init__(self, client, root_prefix: str, source_id: str):
        self.client = client
        self.prefix = "/".join(
            p for p in (root_prefix.strip("/"), "streams", source_id) if p)
        self._seq: int | None = None

    def read_all(self) -> list[tuple[int, list]]:
        """Contiguous durable prefix, stopping at the first gap or corrupt
        object — exactly SnapshotLog._scan's torn-tail rule. Skipping a
        hole would desynchronize the replay+skip resume protocol (the
        skip counter assumes the replayed records are a PREFIX of what
        the reader re-emits)."""
        records: list = []
        expect = 0
        for obj in sorted(self.client.list_objects(self.prefix + "/"),
                          key=lambda o: o["key"]):
            try:
                seq = int(obj["key"].rsplit("/", 1)[-1])
            except ValueError:
                continue  # foreign object under the prefix
            if seq != expect:
                break  # gap: a later commit without its predecessor
            data = self.client.get_object(obj["key"])
            if not data.startswith(_MAGIC) \
                    or len(data) < len(_MAGIC) + _HDR.size:
                break
            length, crc = _HDR.unpack_from(data, len(_MAGIC))
            payload = data[len(_MAGIC) + _HDR.size:
                           len(_MAGIC) + _HDR.size + length]
            if len(payload) != length or zlib.crc32(payload) != crc:
                break  # interrupted upload: prefix ends here
            records.append(_safe_loads(payload))
            expect += 1
        self._seq = expect  # next append overwrites a torn slot
        return records

    def _next_seq(self) -> int:
        """Key listing only — no GETs/unpickling just to number an append
        (the records themselves are read once by the driver's cache).
        Appends after the CONTIGUOUS prefix: a torn/corrupt object's slot
        gets overwritten, matching read_all's prefix rule."""
        keys = set()
        for obj in self.client.list_objects(self.prefix + "/"):
            try:
                keys.add(int(obj["key"].rsplit("/", 1)[-1]))
            except ValueError:
                pass
        seq = 0
        while seq in keys:
            seq += 1
        return seq

    def append(self, time: int, entries: list) -> None:
        if self._seq is None:
            self._seq = self._next_seq()
        payload = pickle.dumps((time, entries),
                               protocol=pickle.HIGHEST_PROTOCOL)
        body = _MAGIC + _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        key = f"{self.prefix}/{self._seq:016d}"

        def _put() -> None:
            faults.hit("persistence.s3.put", key=key, time=time)
            self.client.put_object(key, body)

        # whole-object PUTs are atomic, so a retry simply overwrites the
        # failed attempt's slot; _seq advances only after success
        _retrying_write(_put, f"PUT {key}")
        self._seq += 1

    def close(self) -> None:
        pass


class MockLog:
    """In-memory log living on the Backend object, surviving re-runs that
    reuse the same ``pw.persistence.Backend.mock()`` instance."""

    def __init__(self, store: dict, source_id: str):
        self._records = store.setdefault(source_id, [])

    def read_all(self) -> list[tuple[int, list]]:
        return list(self._records)

    def append(self, time: int, entries: list) -> None:
        self._records.append((time, entries))

    def close(self) -> None:
        pass


class _RecordingSession:
    """Session proxy for a restarted source: buffers live entries (with
    their source offsets) for durable append at the next commit. For
    non-seekable sources it additionally drops the first ``skip`` live
    entries — those were replayed from the snapshot log (the reference's
    offset-continuation, expressed as replay+skip). Duck-types
    io._datasource.Session (push/drain/close/closed).

    **Durability seals**: the streaming loop stamps ``seal(tick)``
    immediately before draining the inner session for tick ``tick``, so
    every entry under a seal was drained — and therefore fully processed
    — by that tick. The commit loop then takes exactly the prefix sealed
    at ticks <= the bridge's resolved watermark: an entry becomes durable
    only once its tick provably retired, at any in-flight depth."""

    def __init__(self, inner, skip: int):
        self._inner = inner
        self._skip = skip
        self.pending: list = []  # (key, row, diff, offset)
        # (tick, cumulative pending length at seal time), tick-ascending.
        # The mutex serializes reader-thread pushes against the commit
        # loop's seal/take (a push between the take's slice and rebind
        # would otherwise be dropped from durability forever).
        self._seals: list[tuple[int, int]] = []
        self._mutex = create_lock("RecordingSession._mutex")
        self.closed = inner.closed
        self.stopping = inner.stopping

    @property
    def stop_requested(self) -> bool:
        return self.stopping.is_set()

    def sleep(self, seconds: float) -> bool:
        return self._inner.sleep(seconds)

    def push(self, key, row, diff: int = 1, offset=None) -> None:
        if self._skip > 0:
            self._skip -= 1
            return
        with self._mutex:
            self.pending.append((key, row, diff, offset))
        self._inner.push(key, row, diff)

    def seal(self, tick: int) -> None:
        """Mark everything pushed so far as belonging to ``tick``'s drain
        (called right before the drain, so sealed ⊆ processed-by-tick)."""
        with self._mutex:
            n = len(self.pending)
            if self._seals and self._seals[-1][1] == n:
                # idle tick: the existing seal already covers these
                # entries at an OLDER tick — keep it (re-stamping to the
                # newer tick would shrink what a frozen watermark may
                # commit); the list only grows when entries do
                return
            self._seals.append((tick, n))

    def take_sealed(self, watermark: int) -> list:
        """Remove and return every pending entry under a seal with tick
        <= ``watermark`` — the longest durable-eligible prefix."""
        with self._mutex:
            n = 0
            cut = 0
            for i, (tick, count) in enumerate(self._seals):
                if tick > watermark:
                    break
                n = count
                cut = i + 1
            if cut:
                self._seals = [(t, c - n) for t, c in self._seals[cut:]]
            if n == 0:
                return []
            entries, self.pending = self.pending[:n], self.pending[n:]
            return entries

    def drain(self) -> list:
        return self._inner.drain()

    def close(self) -> None:
        self._inner.close()


class PersistenceDriver:
    """Engine side of ``pw.persistence.Config`` (python half at
    pathway_tpu/persistence/__init__.py; reference equivalent
    persistence/__init__.py:12,89 + src/persistence/tracker.rs)."""

    def __init__(self, config):
        self.config = config
        backend = config.backend
        self.kind = backend.kind
        self._s3 = None
        if self.kind == "s3":
            # native SigV4 client (io/s3/_client.py): snapshots become
            # objects under <bucket>/<prefix>/streams/<sid>/<seq>
            from pathway_tpu.io.s3._client import (S3Client,
                                                   client_from_settings,
                                                   split_bucket_prefix)

            settings = backend.options.get("bucket_settings")
            bucket, prefix = split_bucket_prefix(
                backend.path or "",
                getattr(settings, "bucket_name", None) if settings else None)
            if settings is not None:
                self._s3 = client_from_settings(settings, bucket=bucket)
            else:
                self._s3 = S3Client(bucket=bucket)  # env credential chain
            self.root = prefix
        elif self.kind == "azure":
            # Azure Blob via the in-repo SharedKey/SAS client; blob surface
            # duck-types S3Client so the object-per-commit log is shared
            from pathway_tpu.io.azure_blob import client_from_backend

            self._s3, self.root = client_from_backend(backend)
        elif self.kind == "filesystem":
            self.root = backend.path
            os.makedirs(os.path.join(self.root, "streams"), exist_ok=True)
        elif self.kind == "mock":
            if not hasattr(backend, "_mock_store"):
                backend._mock_store = {}
            self.root = None
        else:
            raise ValueError(f"unknown persistence backend {self.kind!r}")
        self._backend = backend
        self._sessions: list[tuple[str, Any, Any]] = []  # (sid, log, rec_session)
        self._restore_time: int | None = None
        self._record_cache: dict[str, list] = {}  # sid → records (read once)
        self._attached_ids: set[str] = set()
        # -- commit instrumentation (read via stats(); /metrics + /status) --
        self.commits = 0                 # commit() calls
        self.commits_with_data = 0       # commits that appended >= 1 record
        self.entries_committed = 0
        self.last_commit_watermark = 0   # durability frontier (monotone)
        self.last_commit_tick = 0        # loop tick at the last commit
        self.last_inflight_at_commit = 0  # bridge depth when committing
        self.commit_wait = _WaitHistogram()

    # -- identity ----------------------------------------------------------
    def _source_id(self, datasource) -> str:
        pid = getattr(datasource, "persistent_id", None)
        if pid:
            return str(pid)
        # `_uid` is a process-wide construction counter: stable only if the
        # program builds the same sources in the same order every run.
        import logging

        logging.getLogger(__name__).warning(
            "source %r has no persistent_id; falling back to construction "
            "order (%s-%s) — adding/reordering sources between runs will "
            "mismatch snapshot logs. Pass persistent_id= to the connector.",
            datasource.name, datasource.name, datasource._uid)
        return f"{datasource.name}-{datasource._uid}"

    def _log_for(self, source_id: str):
        if self.kind == "mock":
            return MockLog(self._backend._mock_store, source_id)
        if self._s3 is not None:
            return S3SnapshotLog(self._s3, self.root, source_id)
        return SnapshotLog(os.path.join(self.root, "streams",
                                        source_id + ".snap"))

    # -- runtime API (called by StreamingRuntime) --------------------------
    def _records(self, sid: str) -> list:
        """Read (and cache) a source's log records — restore_time and
        attach_source both need them; unpickle only once per startup."""
        if sid not in self._record_cache:
            self._record_cache[sid] = self._log_for(sid).read_all()
        return self._record_cache[sid]

    def restore_time(self) -> int:
        """Last committed logical time across all logged sources (0 = fresh)."""
        if self._restore_time is not None:
            return self._restore_time
        last = 0
        if self.kind == "mock":
            sids = list(self._backend._mock_store.keys())
        elif self._s3 is not None:
            prefix = "/".join(p for p in (self.root.strip("/"), "streams")
                              if p) + "/"
            sids = sorted({
                obj["key"][len(prefix):].split("/", 1)[0]
                for obj in self._s3.list_objects(prefix)})
        else:
            streams = os.path.join(self.root, "streams")
            sids = [f[:-5] for f in os.listdir(streams)
                    if f.endswith(".snap")] if os.path.isdir(streams) else []
        for sid in sids:
            for t, _ in self._records(sid):
                last = max(last, t)
        self._restore_time = last
        return last

    def attach_source(self, datasource, session):
        """Replay this source's durable prefix into ``session`` and return
        the recording proxy the live reader thread must push into.

        Two continuation protocols (reference: connectors/mod.rs:215-368 —
        ``rewind_from_disk_snapshot`` then continue from stored offsets):

        - **seekable** sources (define ``seek(replayed_entries)``) receive
          every replayed ``(key, row, diff, offset)`` and position their
          reader past the durable prefix themselves; nothing live is
          dropped. This is exact under reordering and file mutation.
        - otherwise the source is assumed to re-emit the identical entry
          sequence on restart, and the first N live pushes are dropped.
        """
        sid = self._source_id(datasource)
        if sid in self._attached_ids:
            raise ValueError(
                f"two persisted sources share the id {sid!r} — their snapshot "
                "logs would cross-replay into each other's tables. Give each "
                "connector a unique persistent_id.")
        self._attached_ids.add(sid)
        log = self._log_for(sid)
        replayed: list = []
        for _t, entries in self._records(sid):
            for entry in entries:
                key, row, diff = entry[0], entry[1], entry[2]
                offset = entry[3] if len(entry) > 3 else None
                session.push(key, row, diff)
                replayed.append((key, row, diff, offset))
        from pathway_tpu.engine.offsets import OffsetAntichain

        antichain = OffsetAntichain.from_entries(
            off for _k, _r, _d, off in replayed)
        if antichain and hasattr(datasource, "seek_offsets"):
            # partitioned source: continue each partition past its durable
            # frontier (reference OffsetAntichain, persistence/frontier.rs)
            datasource.seek_offsets(antichain)
            skip = 0
        elif hasattr(datasource, "seek"):
            datasource.seek(replayed)
            skip = 0
        else:
            if replayed:
                import logging

                logging.getLogger(__name__).warning(
                    "resuming source %r with the prefix-replay protocol: the "
                    "reader is assumed to re-emit the identical first %d "
                    "entries on restart. Sources that re-read *current* "
                    "state (databases, compacted topics) need a seek() "
                    "implementation for exact resume.", sid, len(replayed))
            skip = len(replayed)
        rec = _RecordingSession(session, skip=skip)
        self._sessions.append((sid, log, rec))
        return rec

    def seal(self, tick: int) -> None:
        """Stamp a durability seal on every recorded source (streaming
        loop, right before the tick's drain)."""
        for _sid, _log, rec in self._sessions:
            rec.seal(tick)

    def commit(self, time: int, watermark: int | None = None,
               inflight: int = 0) -> None:
        """Durably record entries whose processing is provably complete.

        ``watermark=None`` — synchronous callers and the end-of-stream
        flush: everything pushed so far is sealed at ``time`` and
        committed (the caller holds hard-barrier semantics: ``time`` is
        fully processed when this runs).

        With a watermark — the pipelined streaming loop: only entries
        sealed at ticks <= ``watermark`` (the device bridge's resolved
        prefix) are appended, in a record carrying the *watermark* tick.
        Either way the log invariant is the same: a record's presence
        implies its time was fully processed — now held exactly, at any
        in-flight depth, instead of by draining the bridge first.
        Transient backend write failures retry inside the log's append
        (``_retrying_write``)."""
        t0 = _time.perf_counter()
        if watermark is None:
            watermark = time
            self.seal(time)
        # fault point between reading the watermark and the durable
        # append: a crash here loses nothing (the sealed entries are
        # re-emitted by the reader on restart, never skipped)
        faults.hit("persistence.commit", time=time, watermark=watermark)
        wrote = False
        for sid, log, rec in self._sessions:
            entries = rec.take_sealed(watermark)
            if entries:
                log.append(watermark, entries)
                self.entries_committed += len(entries)
                wrote = True
        self.commits += 1
        self.last_commit_tick = max(self.last_commit_tick, time)
        self.last_commit_watermark = max(self.last_commit_watermark,
                                         watermark)
        self.last_inflight_at_commit = inflight
        if wrote:
            self.commits_with_data += 1
            self.commit_wait.observe((_time.perf_counter() - t0) * 1e3)

    def stats(self) -> dict:
        """Commit-watermark snapshot for /status and the dashboard."""
        return {
            "commits": self.commits,
            "commits_with_data": self.commits_with_data,
            "entries_committed": self.entries_committed,
            "watermark": self.last_commit_watermark,
            "lag_ticks": max(0, self.last_commit_tick
                             - self.last_commit_watermark),
            "inflight_at_commit": self.last_inflight_at_commit,
            "write_retries": write_retries_total(),
            "commit_wait_ms_sum": round(self.commit_wait.sum_ms, 3),
            "commit_wait_count": self.commit_wait.count,
        }

    def close(self) -> None:
        for _sid, log, _rec in self._sessions:
            log.close()
