"""Checkpoint/resume driver for the streaming runtime.

Rebuild of the reference's persistence stack (src/persistence/ —
``WorkerPersistentStorage`` tracker.rs:20, ``MetadataAccessor`` state.rs:20,
snapshot record/replay in src/connectors/snapshot.rs + mod.rs:215-368):
each source's parsed entries are appended to a durable **snapshot log**
together with the commit timestamp; on restart the driver replays every
logged entry into the source's session (state is rebuilt by re-running the
dataflow over the replayed prefix) and suppresses the first N live entries
the re-started reader emits, N being the number durably logged — the
"rewind then continue from stored offsets" protocol of the reference,
expressed as replay+skip so *any* deterministic reader gets exactly-once
input without a per-reader seek API.

The log is authoritative (no separate metadata file to keep consistent):
records are length-prefixed, CRC32-checksummed pickles decoded by a
RESTRICTED unpickler (class whitelist below — a snapshot written by an
attacker with access to shared storage must not execute code on resume),
fsynced per commit; a truncated or corrupted tail record (crash
mid-append, bit rot) is detected and dropped on load. This mirrors the
reference's rule that only data finalized at the last *committed* frontier
is recovered (state.rs:120-226) — the reference gets decode safety for
free from serde/bincode's data-only model; the Python build has to
enforce it explicitly.

Backends: ``filesystem`` (a directory of per-source logs) and ``mock``
(in-memory, state kept on the Backend object — the test double, like the
reference's mock metadata backend).
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import struct
import time as _time
import zlib
from typing import Any, Callable

from pathway_tpu.engine.locking import blocking_call, create_lock

from pathway_tpu.testing import faults

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<QI")  # payload length, CRC32(payload)
_MAGIC = b"PWSNAP01"  # format marker; bump the digit on layout changes
_STATE_MAGIC = b"PWOPSNAP1"  # operator-state snapshot blob marker

# Decode whitelist: data classes that legitimately appear inside logged
# (time, [(key, row, diff, offset), ...]) records — engine Values
# (internals/keys.Pointer, internals/json.Json, numpy arrays, datetimes)
# and plain containers. Anything else (os.system, builtins.eval,
# functools.partial, ...) is refused at load time.
_SAFE_GLOBALS = {
    ("builtins", n) for n in
    ("list", "tuple", "dict", "set", "frozenset", "bytearray", "complex")
} | {
    ("pathway_tpu.internals.keys", "Pointer"),
    ("pathway_tpu.internals.json", "Json"),
    ("datetime", "datetime"), ("datetime", "date"), ("datetime", "time"),
    ("datetime", "timedelta"), ("datetime", "timezone"),
    # the build's canonical datetime/duration value types host-side are
    # pandas Timestamp/Timedelta (internals/expressions/date_time.py)
    ("pandas._libs.tslibs.timestamps", "_unpickle_timestamp"),
    ("pandas._libs.tslibs.timestamps", "Timestamp"),
    ("pandas._libs.tslibs.timedeltas", "_timedelta_unpickle"),
    ("pandas._libs.tslibs.timedeltas", "Timedelta"),
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
}


class ReadOnlyPersistenceError(RuntimeError):
    """A mutation (append/commit/truncate/compact/snapshot write) was
    attempted through a driver opened with ``read_only=True``. Raised by
    name so a replica that would otherwise corrupt its primary's WAL or
    snapshot generations dies loudly instead (engine/replica.py opens the
    primary's root exactly this way)."""


class FencedPrimaryError(RuntimeError):
    """A writer discovered that the persistence root's fencing epoch
    moved past its own: a replica was PROMOTED to primary while this
    process still believed it held the write lease (e.g. a SIGSTOPped
    primary resumed after failover). Raised by name — naming both
    epochs — before any byte lands in the WAL or a snapshot manifest,
    so a zombie primary self-demotes loudly instead of splicing a
    second timeline into the shared root (README "Write-path
    failover")."""

    def __init__(self, held_epoch: int, root_epoch: int, what: str):
        self.held_epoch = held_epoch
        self.root_epoch = root_epoch
        super().__init__(
            f"fenced primary: this writer holds fencing epoch "
            f"{held_epoch} but the persistence root is at epoch "
            f"{root_epoch} — a newer primary was promoted; refusing "
            f"{what} and self-demoting (restart this process as a "
            f"replica of the new primary)")


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _SAFE_GLOBALS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"snapshot log references forbidden global {module}.{name} — "
            "refusing to decode (possible tampering)")


def _safe_loads(payload: bytes):
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


# ---------------------------------------------------------------------------
# snapshot/compaction knobs (cadence knobs live in engine/streaming.py)
# ---------------------------------------------------------------------------

def _keep_generations() -> int:
    """Snapshot generations retained (>= 1). The WAL is truncated only to
    the OLDEST retained generation's tick, so a corrupt newest snapshot
    can always fall back one generation and still find its suffix."""
    from pathway_tpu.internals.config import _env_int

    return max(1, _env_int("PATHWAY_SNAPSHOT_KEEP_GENERATIONS", 2))


def _compact_enabled() -> bool:
    """PATHWAY_SNAPSHOT_COMPACT=0 writes snapshots without truncating the
    WAL (the recovery-equivalence property tests compare snapshot+suffix
    replay against full-WAL replay over the same root)."""
    return os.environ.get("PATHWAY_SNAPSHOT_COMPACT", "1").lower() not in (
        "0", "false", "off", "no")


def _restore_enabled() -> bool:
    """PATHWAY_SNAPSHOT_RESTORE=0 ignores existing snapshots on startup
    (full-WAL replay — only sound while compaction is disabled or no
    snapshot was ever written)."""
    return os.environ.get("PATHWAY_SNAPSHOT_RESTORE", "1").lower() not in (
        "0", "false", "off", "no")


def _atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp + fsync + rename, like flight_recorder.atomic_write_json but
    for a binary blob: a crash mid-write never leaves a truncated file at
    ``path`` and never clobbers a previous good one."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        # crash edge between the data fsync and the rename: the tmp is
        # durable but invisible — recovery must fall back to the
        # previous good file at ``path``
        faults.hit("persistence.atomic.replace", path=str(path))
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# transient-write retries (shared by the file and object-store logs)
# ---------------------------------------------------------------------------

# process-wide retry counter, exported on /metrics as
# ``pathway_tpu_persistence_write_retries`` (Prometheus counters are
# process-scoped by convention — several drivers in one process share it)
_retry_lock = create_lock("persistence._retry_lock")
_write_retries_total = 0


def write_retries_total() -> int:
    with _retry_lock:
        return _write_retries_total


def _retrying_write(body: Callable[[], None], what: str) -> None:
    """Run one durable write (append+fsync, object PUT), retrying
    transient failures with the shared exponential backoff + full jitter
    schedule (internals/retries.py). ``body`` must be safe to re-run from
    scratch: the file log truncates its torn tail before every attempt
    and object PUTs are atomic whole-object writes. Exhausting
    ``PATHWAY_PERSISTENCE_WRITE_RETRIES`` (default 3; 0 disables
    retries) re-raises the last error — the streaming commit loop then
    escalates it per ``terminate_on_error``."""
    from pathway_tpu.internals.config import _env_int

    global _write_retries_total
    budget = max(0, _env_int("PATHWAY_PERSISTENCE_WRITE_RETRIES", 3))
    strategy = None
    attempt = 0
    while True:
        try:
            body()
            return
        except Exception as e:
            if attempt >= budget:
                raise
            if strategy is None:
                from pathway_tpu.internals.retries import \
                    ExponentialBackoffRetryStrategy

                strategy = ExponentialBackoffRetryStrategy(
                    initial_delay_ms=max(1, _env_int(
                        "PATHWAY_PERSISTENCE_RETRY_INITIAL_MS", 50)),
                    backoff_factor=2.0,
                    max_delay_ms=max(1, _env_int(
                        "PATHWAY_PERSISTENCE_RETRY_MAX_MS", 2000)),
                    jitter=True)
            delay = strategy.delay_for_attempt(attempt)
            with _retry_lock:
                _write_retries_total += 1
            logger.warning(
                "transient persistence write failure (%s): %s: %s — "
                "retry %d/%d in %.3fs", what, type(e).__name__, e,
                attempt + 1, budget, delay)
            _time.sleep(delay)
            attempt += 1


class _WaitHistogram:
    """Fixed-bucket commit-wait histogram, Prometheus-exposed as
    ``pathway_tpu_commit_wait_ms`` — how long each durable commit (append
    + fsync/PUT incl. retries) held the loop."""

    BUCKETS_MS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  1000.0)

    def __init__(self):
        self.counts = [0] * (len(self.BUCKETS_MS) + 1)
        self.sum_ms = 0.0
        self.count = 0

    def observe(self, ms: float) -> None:
        i = 0
        for b in self.BUCKETS_MS:
            if ms <= b:
                break
            i += 1
        self.counts[i] += 1
        self.sum_ms += ms
        self.count += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative count)], +Inf last (exposition format)."""
        out: list[tuple[float, int]] = []
        cum = 0
        for b, c in zip(self.BUCKETS_MS, self.counts):
            cum += c
            out.append((b, cum))
        out.append((float("inf"), cum + self.counts[-1]))
        return out


def record_epoch(rec) -> int:
    """Fencing epoch a log record was written under. Records are
    ``(time, entries)`` tuples from roots that never saw a promotion
    (epoch 0 — every pre-failover root stays byte-compatible) or
    ``(time, entries, epoch)`` once a promotion bumped the root's
    epoch; unpack by index so both shapes read identically."""
    return int(rec[2]) if len(rec) > 2 else 0


class SnapshotLog:
    """Append-only framed, checksummed, restricted-pickle log of
    (time, entries[, epoch]) records (``epoch`` — the writer's fencing
    epoch — is stamped only when nonzero, keeping pre-failover logs
    byte-identical)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._f = None

    def _scan(self) -> tuple[list[tuple[int, list]], int]:
        """(intact records, byte offset of the end of the last intact one).
        A torn tail record — crash mid-append — is excluded from both.
        Within one log, record epochs are non-decreasing (a promotion
        only ever bumps the root's epoch); a record whose epoch is
        BELOW its predecessor's is a fenced zombie's write that raced
        the fencing check — recovery truncates at it, loudly, keeping
        the single post-promotion timeline."""
        records: list = []
        if not os.path.exists(self.path):
            return records, 0
        with open(self.path, "rb") as f:
            data = f.read()
        if not data:
            return records, 0
        if len(data) < len(_MAGIC) and _MAGIC.startswith(data):
            # crash during the very first append, mid-magic: an empty log
            # with a torn tail, not an alien file
            return records, 0
        if not data.startswith(_MAGIC):
            # refuse to guess: silently reading an alien/older layout as
            # empty would wipe it on the next append
            raise ValueError(
                f"{self.path}: not a {_MAGIC.decode()} snapshot log — "
                "refusing to read or overwrite it")
        pos = len(_MAGIC)
        high_epoch = 0
        while pos + _HDR.size <= len(data):
            length, crc = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + length
            if end > len(data):
                # incomplete record: a torn tail (crash mid-append) — or
                # a corrupted LENGTH header mid-log, which is
                # indistinguishable byte-wise; say how much is dropped
                # either way (the next append truncates it)
                logger.warning(
                    "%s: incomplete record at byte %d (%d trailing "
                    "byte(s) dropped: torn tail, or a corrupt length "
                    "header hiding later records)", self.path, pos,
                    len(data) - pos)
                break
            payload = data[pos + _HDR.size:end]
            bad = zlib.crc32(payload) != crc
            if not bad:
                try:
                    rec = _safe_loads(payload)
                except pickle.UnpicklingError:
                    raise  # forbidden global = tampering, not a torn tail
                except Exception:
                    bad = True
            if not bad:
                epoch = record_epoch(rec)
                if epoch < high_epoch:
                    logger.error(
                        "%s: fenced-zombie write at byte %d — record at "
                        "tick %s carries fencing epoch %d below the "
                        "log's established epoch %d (a demoted primary "
                        "raced the fencing check) — truncating at it to "
                        "keep the single post-promotion timeline",
                        self.path, pos, rec[0], epoch, high_epoch)
                    break
                high_epoch = epoch
            if bad:
                # a CRC/decode failure on the LAST framed record is the
                # ordinary torn tail; one with more bytes behind it is
                # mid-log corruption (bit rot, partial overwrite) — the
                # per-record CRC catches it BEFORE the unpickler sees
                # garbage, and recovery truncates at the first bad
                # record, loudly, dropping whatever followed
                if end < len(data):
                    logger.error(
                        "%s: corrupt record at byte %d (mid-log, %d bytes "
                        "follow) — truncating the log at the first bad "
                        "record; %d later byte(s) of history are "
                        "unrecoverable and will be re-ingested live",
                        self.path, pos, len(data) - end, len(data) - pos)
                else:
                    logger.warning(
                        "%s: torn tail record at byte %d dropped (crash "
                        "mid-append)", self.path, pos)
                break
            records.append(rec)
            pos = end
        return records, pos

    def read_all(self) -> list[tuple[int, list]]:
        return self._scan()[0]

    def append(self, time: int, entries: list, epoch: int = 0) -> int:
        if self._f is None:
            # truncate any torn tail record before appending, or every later
            # record would sit behind unreadable bytes forever
            _records, valid = self._scan()
            self._f = open(self.path, "ab")
            if self._f.tell() != valid:
                self._f.truncate(valid)
                self._f.seek(valid)
            if valid == 0:
                self._f.write(_MAGIC)
        rec = (time, entries, epoch) if epoch else (time, entries)
        payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        crc = zlib.crc32(payload)
        if faults.armed("persistence.append.corrupt"):
            # test hook: flip payload bytes AFTER the CRC was computed —
            # the written record is a mid-log corruption _scan must catch
            mutable = bytearray(payload)
            faults.hit("persistence.append.corrupt", path=self.path,
                       time=time, payload=mutable)
            payload = bytes(mutable)
        start = self._f.tell()

        def _write() -> None:
            # re-entry after a failed attempt: truncate whatever the torn
            # attempt left (a header without its payload) before
            # rewriting, or every later record would sit behind
            # unreadable bytes. First attempt: size == start, a no-op.
            # The file is opened in append mode, so writes land at the
            # (possibly truncated-back) end regardless of seek position.
            self._f.truncate(start)
            self._f.seek(start)
            faults.hit("persistence.append", path=self.path, time=time)
            self._f.write(_HDR.pack(len(payload), crc))
            # fault point between header and payload: an armed action
            # aborts here leaving exactly the torn-tail record _scan
            # must drop
            faults.hit("persistence.append.torn", path=self.path, time=time)
            self._f.write(payload)
            self._f.flush()
            faults.hit("persistence.fsync", path=self.path, time=time)
            # fsync is a known-blocking call: the sanitizer asserts no
            # engine lock is held while the durability write stalls
            with blocking_call("persistence.fsync"):
                os.fsync(self._f.fileno())

        _retrying_write(_write, f"append to {self.path}")
        return _HDR.size + len(payload)

    def truncate_to(self, tick: int) -> int:
        """WAL compaction: atomically rewrite the log keeping only records
        with time > ``tick`` (the suffix a durable snapshot does not
        cover). Returns the number of ENTRIES dropped. Record times are
        monotone (commit watermarks), so the kept records are a
        contiguous byte suffix — copied verbatim, never re-pickled; only
        the dropped prefix (plus the first kept record) is decoded. The
        previous file is replaced only after the rewrite is fsynced, so a
        crash mid-compaction leaves either the old or the new log —
        never a partial one."""
        self.close()  # the append handle's position is about to be wrong
        if not os.path.exists(self.path):
            return 0
        with open(self.path, "rb") as f:
            data = f.read()
        if not data.startswith(_MAGIC):
            return 0  # alien/torn-magic file: _scan's rules own this case
        pos = len(_MAGIC)
        dropped = 0
        cut = None  # byte offset of the first KEPT record
        while pos + _HDR.size <= len(data):
            length, crc = _HDR.unpack_from(data, pos)
            end = pos + _HDR.size + length
            if end > len(data):
                break
            payload = data[pos + _HDR.size:end]
            if zlib.crc32(payload) != crc:
                break
            try:
                rec = _safe_loads(payload)
                t, entries = rec[0], rec[1]
            except Exception:
                break
            if t > tick:
                cut = pos
                break
            dropped += len(entries)
            pos = end
        if dropped == 0:
            return 0
        body = _MAGIC + (data[cut:] if cut is not None else b"")
        with blocking_call("persistence.compact"):
            _atomic_write_bytes(self.path, body)
        return dropped

    def truncate_after(self, tick: int) -> int:
        """Promotion-time suffix truncation — the inverse cut of
        :meth:`truncate_to`: atomically rewrite the log keeping only
        records with time <= ``tick``. The dead primary's final commit
        may have landed in SOME logs but not others (it died
        mid-commit); the promoted replica applied only complete ticks,
        so every record past its applied tick is an incomplete commit
        that must not survive into the new timeline. Returns entries
        dropped."""
        self.close()
        records, _valid = self._scan()
        kept = [r for r in records if r[0] <= tick]
        if len(kept) == len(records):
            return 0
        dropped = sum(len(r[1]) for r in records if r[0] > tick)
        body = bytearray(_MAGIC)
        for rec in kept:
            payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
            body += _HDR.pack(len(payload), zlib.crc32(payload))
            body += payload
        with blocking_call("persistence.compact"):
            _atomic_write_bytes(self.path, bytes(body))
        return dropped

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class S3SnapshotLog:
    """Object-per-commit snapshot log on S3-compatible storage: each
    append PUTs ``<prefix>/streams/<sid>/<seq:016d>`` containing one
    framed, checksummed record; restore lists the prefix and replays
    objects in key order. Object stores give atomic whole-object PUTs, so
    the torn-tail handling of the file log becomes 'skip a corrupt
    object' (reference: S3 metadata/stream backends,
    src/persistence/metadata_backends/ + connectors/snapshot.rs)."""

    def __init__(self, client, root_prefix: str, source_id: str):
        self.client = client
        self.prefix = "/".join(
            p for p in (root_prefix.strip("/"), "streams", source_id) if p)
        self._seq: int | None = None
        self._purged = False

    def read_all(self) -> list[tuple[int, list]]:
        """Contiguous durable prefix, stopping at the first gap or corrupt
        object — exactly SnapshotLog._scan's torn-tail rule. Skipping a
        hole would desynchronize the replay+skip resume protocol (the
        skip counter assumes the replayed records are a PREFIX of what
        the reader re-emits)."""
        records: list = []
        expect = 0
        objs = []
        for obj in sorted(self.client.list_objects(self.prefix + "/"),
                          key=lambda o: o["key"]):
            try:
                seq = int(obj["key"].rsplit("/", 1)[-1])
            except ValueError:
                continue  # foreign object under the prefix
            objs.append((seq, obj["key"]))
        for i, (seq, key) in enumerate(objs):
            if seq != expect:
                break  # gap: a later commit without its predecessor
            data = self.client.get_object(key)
            bad = (not data.startswith(_MAGIC)
                   or len(data) < len(_MAGIC) + _HDR.size)
            if not bad:
                length, crc = _HDR.unpack_from(data, len(_MAGIC))
                payload = data[len(_MAGIC) + _HDR.size:
                               len(_MAGIC) + _HDR.size + length]
                bad = len(payload) != length or zlib.crc32(payload) != crc
            if bad:
                # per-record CRC: a corrupt object with SUCCESSORS is
                # mid-sequence corruption, not an interrupted tail upload
                # — recovery still stops at the first bad record, loudly
                if i + 1 < len(objs):
                    logger.error(
                        "%s: corrupt snapshot object %s mid-sequence "
                        "(%d later object(s)) — truncating recovery at "
                        "the first bad record", self.prefix, key,
                        len(objs) - i - 1)
                break
            records.append(_safe_loads(payload))
            expect += 1
        self._seq = expect  # next append overwrites a torn slot
        return records

    def _next_seq(self) -> int:
        """Key listing only — no GETs/unpickling just to number an append
        (the records themselves are read once by the driver's cache).
        Appends after the CONTIGUOUS prefix: a torn/corrupt object's slot
        gets overwritten, matching read_all's prefix rule."""
        keys = set()
        for obj in self.client.list_objects(self.prefix + "/"):
            try:
                keys.add(int(obj["key"].rsplit("/", 1)[-1]))
            except ValueError:
                pass
        seq = 0
        while seq in keys:
            seq += 1
        return seq

    def _purge_stale_successors(self) -> None:
        """Delete objects at/past the next append slot before the first
        write of this session. After a mid-sequence corruption (or gap)
        truncated recovery, objects BEYOND the break are leftovers of the
        abandoned timeline — appending in front of them and crashing
        would let a later read_all splice those CRC-valid strays back
        into the replayed history."""
        for obj in list(self.client.list_objects(self.prefix + "/")):
            try:
                seq = int(obj["key"].rsplit("/", 1)[-1])
            except ValueError:
                continue
            if seq >= self._seq:
                self.client.delete_object(obj["key"])

    def append(self, time: int, entries: list, epoch: int = 0) -> int:
        if self._seq is None:
            self._seq = self._next_seq()
        if not self._purged:
            self._purged = True
            self._purge_stale_successors()
        # epoch accepted for log-API parity; object-store roots do not
        # support fencing (no atomic read-modify-write manifest), so the
        # driver keeps epoch 0 there and the record shape is unchanged
        rec = (time, entries, epoch) if epoch else (time, entries)
        payload = pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL)
        crc = zlib.crc32(payload)
        if faults.armed("persistence.append.corrupt"):
            mutable = bytearray(payload)
            faults.hit("persistence.append.corrupt", key=self.prefix,
                       time=time, payload=mutable)
            payload = bytes(mutable)
        body = _MAGIC + _HDR.pack(len(payload), crc) + payload
        key = f"{self.prefix}/{self._seq:016d}"

        def _put() -> None:
            faults.hit("persistence.s3.put", key=key, time=time)
            self.client.put_object(key, body)

        # whole-object PUTs are atomic, so a retry simply overwrites the
        # failed attempt's slot; _seq advances only after success
        _retrying_write(_put, f"PUT {key}")
        self._seq += 1
        return len(body)

    def close(self) -> None:
        pass


class MockLog:
    """In-memory log living on the Backend object, surviving re-runs that
    reuse the same ``pw.persistence.Backend.mock()`` instance. Grows the
    same truncate API as the file log so unit tests exercise snapshot
    compaction without a filesystem."""

    def __init__(self, store: dict, source_id: str):
        self._records = store.setdefault(source_id, [])

    def read_all(self) -> list[tuple[int, list]]:
        return list(self._records)

    def append(self, time: int, entries: list, epoch: int = 0) -> int:
        rec = (time, entries, epoch) if epoch else (time, entries)
        self._records.append(rec)
        # byte-threshold accounting parity with the durable logs
        return len(pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL))

    def truncate_to(self, tick: int) -> int:
        """Drop records covered by a durable snapshot (time <= tick);
        returns entries dropped. In-place slice assignment so every
        holder of the store's list sees the compaction."""
        dropped = sum(len(r[1]) for r in self._records if r[0] <= tick)
        if dropped:
            self._records[:] = [r for r in self._records if r[0] > tick]
        return dropped

    def truncate_after(self, tick: int) -> int:
        """Promotion-time suffix cut (SnapshotLog.truncate_after): drop
        records PAST ``tick`` — the dead primary's incomplete final
        commit; returns entries dropped."""
        kept = [r for r in self._records if r[0] <= tick]
        if len(kept) == len(self._records):
            return 0
        dropped = sum(len(r[1]) for r in self._records if r[0] > tick)
        self._records[:] = kept
        return dropped

    def close(self) -> None:
        pass


def scan_log_bytes(data: bytes,
                   expect_magic: bool) -> tuple[list[tuple[int, list]], int]:
    """Parse intact ``(time, entries)`` records from a (possibly partial)
    snapshot-log byte buffer. ``expect_magic`` is True when ``data``
    begins at byte 0 of the file (the magic header is consumed first).
    Returns ``(records, consumed)`` — ``consumed`` counts bytes of
    ``data`` consumed, magic included. Unlike :meth:`SnapshotLog._scan`,
    an incomplete or checksum-failing tail record is left UNconsumed
    rather than dropped: a live primary may still be mid-append, and the
    tailer (engine/replica.py) simply retries from the same offset on
    its next poll. A record whose fencing epoch regresses below its
    predecessor's (a fenced zombie's write) stops the scan there —
    permanently unconsumed; recovery truncates it (``SnapshotLog._scan``)
    and the tailer never applies it."""
    records: list = []
    pos = 0
    high_epoch = 0
    if expect_magic:
        if not data.startswith(_MAGIC):
            return records, 0  # header not fully written yet
        pos = len(_MAGIC)
    while pos + _HDR.size <= len(data):
        length, crc = _HDR.unpack_from(data, pos)
        end = pos + _HDR.size + length
        if end > len(data):
            break  # incomplete: the primary is mid-append — retry later
        payload = data[pos + _HDR.size:end]
        if zlib.crc32(payload) != crc:
            break  # not yet flushed fully (or corrupt): retry later
        try:
            rec = _safe_loads(payload)
        except Exception:
            break
        epoch = record_epoch(rec)
        if epoch < high_epoch:
            break  # fenced-zombie write: never apply, never consume
        high_epoch = epoch
        records.append(rec)
        pos = end
    return records, pos


class _ReadOnlyLog:
    """Log proxy handed out by a ``read_only=True`` driver: every read
    passes through; every mutation raises :class:`ReadOnlyPersistenceError`
    by name (defense in depth behind the driver-level guards)."""

    def __init__(self, inner):
        self._inner = inner
        self.path = getattr(inner, "path", None)

    def read_all(self):
        return self._inner.read_all()

    def append(self, time, entries, epoch=0):
        raise ReadOnlyPersistenceError(
            "append() on a read-only persistence root — a replica must "
            "never write to its primary's WAL")

    def truncate_to(self, tick):
        raise ReadOnlyPersistenceError(
            "truncate_to() on a read-only persistence root — a replica "
            "must never compact its primary's WAL")

    def truncate_after(self, tick):
        raise ReadOnlyPersistenceError(
            "truncate_after() on a read-only persistence root — a "
            "replica must never rewrite the primary's WAL tail")

    def close(self):
        self._inner.close()


class _RecordingSession:
    """Session proxy for a restarted source: buffers live entries (with
    their source offsets) for durable append at the next commit. For
    non-seekable sources it additionally drops the first ``skip`` live
    entries — those were replayed from the snapshot log (the reference's
    offset-continuation, expressed as replay+skip). Duck-types
    io._datasource.Session (push/drain/close/closed).

    **Durability seals**: the streaming loop stamps ``seal(tick)``
    immediately before draining the inner session for tick ``tick``, so
    every entry under a seal was drained — and therefore fully processed
    — by that tick. The commit loop then takes exactly the prefix sealed
    at ticks <= the bridge's resolved watermark: an entry becomes durable
    only once its tick provably retired, at any in-flight depth."""

    def __init__(self, inner, skip: int):
        self._inner = inner
        self._skip = skip
        self.pending: list = []  # (key, row, diff, offset)
        # (tick, cumulative pending length at seal time), tick-ascending.
        # The mutex serializes reader-thread pushes against the commit
        # loop's seal/take (a push between the take's slice and rebind
        # would otherwise be dropped from durability forever).
        self._seals: list[tuple[int, int]] = []
        # entries drained (processed) but not yet taken by a commit:
        # under QoS ingest budgeting (engine/qos.py) a tick's drain may
        # be PARTIAL, so seals must cover exactly the drained prefix —
        # pushed-but-undrained entries stay past the newest seal and get
        # sealed by the later tick that actually drains them (sealed ⊆
        # processed is preserved at any clip point)
        self._drained = 0
        self._mutex = create_lock("RecordingSession._mutex")
        self.closed = inner.closed
        self.stopping = inner.stopping

    @property
    def stop_requested(self) -> bool:
        return self.stopping.is_set()

    def sleep(self, seconds: float) -> bool:
        return self._inner.sleep(seconds)

    def push(self, key, row, diff: int = 1, offset=None) -> None:
        if self._skip > 0:
            self._skip -= 1
            return
        with self._mutex:
            # the inner push stays INSIDE the mutex: seal_drain drains
            # the inner session and seals pending atomically under it, so
            # an entry must never be recordable (pending) without being
            # drainable (inner) — a push split across the mutex boundary
            # could be sealed at tick t yet processed at t+1, and a
            # snapshot at t would cover it without containing it
            self.pending.append((key, row, diff, offset))
            self._inner.push(key, row, diff)

    def seal(self, tick: int) -> None:
        """Mark everything pushed so far as belonging to ``tick``'s drain
        (called right before the drain, so sealed ⊆ processed-by-tick).
        The full-commit path only (end-of-stream, sync callers): the
        streaming loop's drains all go through :meth:`seal_drain`, and at
        end of stream the re-drain loop has emptied the inner session, so
        sealing the whole pending list never covers an unprocessed
        entry."""
        with self._mutex:
            self._drained = len(self.pending)
            self._seal_locked(tick, self._drained)

    def _seal_locked(self, tick: int, n: int) -> None:
        if self._seals and self._seals[-1][1] == n:
            # idle tick: the existing seal already covers these
            # entries at an OLDER tick — keep it (re-stamping to the
            # newer tick would shrink what a frozen watermark may
            # commit); the list only grows when entries do
            return
        self._seals.append((tick, n))

    def seal_drain(self, tick: int, limit: int | None = None) -> list:
        """Atomically drain the inner session AND seal at ``tick`` under
        the push mutex, so *sealed at <= tick* equals *drained at <= tick*
        EXACTLY. The streaming loop uses this instead of seal-then-drain:
        an entry arriving between a separate seal and the drain would be
        processed at ``tick`` but sealed at ``tick+1`` — harmless for
        WAL-only replay, but fatal for operator-state snapshots (the
        snapshot cut at ``tick`` would already contain it while the WAL
        suffix past ``tick`` replays it again — a double count).

        ``limit`` clips the drain (QoS ingest budgeting): the seal then
        covers exactly the drained prefix — pending rows beyond it belong
        to no seal until a later tick drains them, so a deferred row can
        never be covered by a checkpoint before the engine processed it.
        Push order and drain order coincide (both append under the push
        path), so the drained prefix of the inner queue IS the prefix of
        ``pending``."""
        with self._mutex:
            entries = self._inner.drain(limit)
            self._drained += len(entries)
            self._seal_locked(tick, self._drained)
            return entries

    def take_sealed(self, watermark: int) -> list:
        """Remove and return every pending entry under a seal with tick
        <= ``watermark`` — the longest durable-eligible prefix."""
        with self._mutex:
            n = 0
            cut = 0
            for i, (tick, count) in enumerate(self._seals):
                if tick > watermark:
                    break
                n = count
                cut = i + 1
            if cut:
                self._seals = [(t, c - n) for t, c in self._seals[cut:]]
            if n == 0:
                return []
            entries, self.pending = self.pending[:n], self.pending[n:]
            self._drained -= n
            return entries

    def drain(self, limit: int | None = None) -> list:
        return self._inner.drain(limit)

    def close(self) -> None:
        self._inner.close()


def source_id(datasource) -> str:
    """Stable durable identity of a source (shared by the driver and the
    replica tailer — both sides of the WAL must agree on it)."""
    pid = getattr(datasource, "persistent_id", None)
    if pid:
        return str(pid)
    # `_uid` is a process-wide construction counter: stable only if the
    # program builds the same sources in the same order every run.
    logger.warning(
        "source %r has no persistent_id; falling back to construction "
        "order (%s-%s) — adding/reordering sources between runs will "
        "mismatch snapshot logs. Pass persistent_id= to the connector.",
        datasource.name, datasource.name, datasource._uid)
    return f"{datasource.name}-{datasource._uid}"


class PersistenceDriver:
    """Engine side of ``pw.persistence.Config`` (python half at
    pathway_tpu/persistence/__init__.py; reference equivalent
    persistence/__init__.py:12,89 + src/persistence/tracker.rs)."""

    # class-level defaults so partially-constructed drivers (tests build
    # them via __new__) still read as writable and unfenced
    read_only = False
    fencing_supported = False
    fencing_epoch = 0
    fenced_writes = 0

    def __init__(self, config, read_only: bool = False):
        self.config = config
        backend = config.backend
        self.kind = backend.kind
        # read-only open mode (engine/replica.py): every mutation —
        # commit/append, WAL truncation, snapshot write, generation
        # pruning — raises ReadOnlyPersistenceError by name, so a replica
        # can never damage the primary's durability state. Reads
        # (restore_time / load_snapshot / _records) are untouched.
        self.read_only = bool(read_only)
        self._s3 = None
        if self.kind == "s3":
            # native SigV4 client (io/s3/_client.py): snapshots become
            # objects under <bucket>/<prefix>/streams/<sid>/<seq>
            from pathway_tpu.io.s3._client import (S3Client,
                                                   client_from_settings,
                                                   split_bucket_prefix)

            settings = backend.options.get("bucket_settings")
            bucket, prefix = split_bucket_prefix(
                backend.path or "",
                getattr(settings, "bucket_name", None) if settings else None)
            if settings is not None:
                self._s3 = client_from_settings(settings, bucket=bucket)
            else:
                self._s3 = S3Client(bucket=bucket)  # env credential chain
            self.root = prefix
        elif self.kind == "azure":
            # Azure Blob via the in-repo SharedKey/SAS client; blob surface
            # duck-types S3Client so the object-per-commit log is shared
            from pathway_tpu.io.azure_blob import client_from_backend

            self._s3, self.root = client_from_backend(backend)
        elif self.kind == "filesystem":
            self.root = backend.path
            if not self.read_only:
                os.makedirs(os.path.join(self.root, "streams"),
                            exist_ok=True)
        elif self.kind == "mock":
            if not hasattr(backend, "_mock_store"):
                backend._mock_store = {}
            self.root = None
        else:
            raise ValueError(f"unknown persistence backend {self.kind!r}")
        self._backend = backend
        self._sessions: list[tuple[str, Any, Any]] = []  # (sid, log, rec_session)
        self._restore_time: int | None = None
        self._record_cache: dict[str, list] = {}  # sid → records (read once)
        self._attached_ids: set[str] = set()
        # -- commit instrumentation (read via stats(); /metrics + /status) --
        self.commits = 0                 # commit() calls
        self.commits_with_data = 0       # commits that appended >= 1 record
        self.entries_committed = 0
        self.last_commit_watermark = 0   # durability frontier (monotone)
        self.last_commit_tick = 0        # loop tick at the last commit
        self.last_inflight_at_commit = 0  # bridge depth when committing
        self.commit_wait = _WaitHistogram()
        # -- operator-state snapshots + WAL compaction ---------------------
        # (filesystem + mock backends; object stores keep WAL-only
        # recovery until they grow an atomic-manifest story)
        self.snapshots_supported = self.kind in ("filesystem", "mock")
        self._snap_dir = (os.path.join(self.root, "snapshots")
                          if self.kind == "filesystem" else None)
        self._loaded_snapshot: dict | None = None
        self._snapshot_probed = False
        self._snapshot_warned = False
        # generation validity cache: gens this driver wrote or whose
        # state blob already passed its checksum (re-verified at most
        # once per generation) vs gens known corrupt — retention must
        # never let a corrupt generation occupy a keep slot (it would
        # prune the valid fallback and truncate the WAL to a tick only
        # the corrupt generation covers)
        self._validated_gens: set[int] = set()
        self._corrupt_gens: set[int] = set()
        self.last_snapshot_tick = 0
        self.snapshot_generation = 0     # 0 = none yet; generations are 1-based
        self.snapshot_bytes = 0
        self.snapshots_total = 0         # written by THIS driver
        self.compactions_total = 0
        self.wal_replayable_entries = 0  # entries a restart would replay
        self.wal_bytes_since_snapshot = 0
        # durable entries NOT covered by the newest snapshot (freshly
        # committed ones plus a restart's replayed suffix): the
        # no-empty-churn guard — a snapshot is only worth writing while
        # this is non-zero
        self.wal_entries_uncovered = 0
        # per-source compact resume frontier, maintained on every commit:
        # entry/insert counts, per-file positions (fs offsets) and the
        # partition antichain — what the manifest stores so seek-capable
        # sources can continue past a COMPACTED prefix
        self._frontiers: dict[str, dict] = {}
        # -- write-path failover fencing (README "Write-path failover") ----
        # The root carries a monotone fencing epoch in an fsynced manifest
        # (<root>/epoch.json, PATHWAY_FLEET_EPOCH_PATH to override; mock
        # roots keep it on the Backend object). A writable driver ADOPTS
        # the existing epoch at open; promotion bumps it (claim_epoch);
        # every commit/snapshot first re-reads the manifest and raises
        # FencedPrimaryError when the root moved past this writer's epoch
        # — a zombie ex-primary self-demotes before any byte lands.
        # Object-store roots have no atomic read-modify-write manifest;
        # fencing stays off there (epoch 0, checks pass).
        self.fencing_supported = self.kind in ("filesystem", "mock")
        self.fenced_writes = 0
        self.fencing_epoch = self.read_epoch() if self.fencing_supported \
            else 0

    # -- fencing epoch (write-path failover) -------------------------------
    def epoch_path(self) -> str | None:
        """Filesystem path of the fencing-epoch manifest (None on
        non-file backends)."""
        if self.kind != "filesystem":
            return None
        return os.environ.get("PATHWAY_FLEET_EPOCH_PATH") \
            or os.path.join(self.root, "epoch.json")

    def read_epoch(self) -> int:
        """The root's current fencing epoch (0 = no promotion ever).
        The manifest is written atomically (tmp + fsync + replace), so
        a crash mid-bump leaves the previous epoch intact — never a
        torn manifest; an unreadable one is treated as epoch 0, loudly
        (fencing degrades open, it never bricks the root)."""
        if self.kind == "mock":
            return int(getattr(self._backend, "_mock_epoch", 0) or 0)
        path = self.epoch_path()
        if path is None:
            return 0
        import json

        try:
            with open(path) as f:
                meta = json.load(f)
            return int(meta.get("epoch", 0))
        except FileNotFoundError:
            return 0
        except Exception as e:
            logger.error(
                "unreadable fencing-epoch manifest %s (%s: %s) — "
                "treating the root as epoch 0 (fencing disabled until "
                "the manifest is rewritten)", path, type(e).__name__, e)
            return 0

    def claim_epoch(self, holder: str, min_epoch: int = 0) -> int:
        """Atomically bump the root's fencing epoch past every epoch any
        writer ever held (and past ``min_epoch``, the router's election
        hint) and adopt it — the promotion step that fences the dead
        (or SIGSTOP-zombied) primary out of the write path forever."""
        if self.read_only:
            raise ReadOnlyPersistenceError(
                "claim_epoch() on a read-only persistence root — flip "
                "the driver writable (promote) before claiming")
        if not self.fencing_supported:
            raise ValueError(
                f"fencing epochs are not supported on the {self.kind!r} "
                "persistence backend (no atomic manifest)")
        new = max(self.read_epoch() + 1, int(min_epoch))
        # fault point: a candidate dying INSIDE the claim must leave the
        # previous epoch manifest intact (the atomic write never ran)
        faults.hit("persistence.epoch.claim", holder=str(holder),
                   epoch=new)
        if self.kind == "mock":
            self._backend._mock_epoch = new
        else:
            import json

            meta = {"format": "pwepoch1", "epoch": new,
                    "holder": str(holder), "bumped_at": _time.time()}
            with blocking_call("persistence.epoch.claim"):
                _atomic_write_bytes(self.epoch_path(),
                                    json.dumps(meta).encode())
        self.fencing_epoch = new
        logger.warning(
            "fencing epoch bumped to %d by %r — every writer still "
            "holding an older epoch is fenced out of this root", new,
            holder)
        return new

    def check_fenced(self, what: str) -> None:
        """Refuse a durable write if the root's epoch moved past this
        writer's (a newer primary was promoted). Called at the top of
        every commit() and write_snapshot() — the fencing read happens
        BEFORE any byte of the write lands."""
        if not self.fencing_supported or self.read_only:
            return
        root_epoch = self.read_epoch()
        if root_epoch > self.fencing_epoch:
            self.fenced_writes += 1
            raise FencedPrimaryError(self.fencing_epoch, root_epoch, what)

    def promote(self, holder: str, complete_tick: int,
                min_epoch: int = 0) -> tuple[int, int]:
        """Flip a replica's read-only driver into the fleet's new
        writable primary: re-read the root fresh (the hydration-time
        caches are stale by now), bump+adopt the fencing epoch, and
        drop the dead primary's incomplete final commit — every record
        past ``complete_tick`` (the last COMPLETE tick the promoting
        replica applied; a mid-commit death leaves later records in
        SOME logs only). Returns ``(max_tick_seen, epoch)`` where
        ``max_tick_seen`` is the highest tick present in any log BEFORE
        the suffix cut — the new primary's time counter starts past it
        so a torn tick number is never reused."""
        if not self.fencing_supported:
            raise ValueError(
                f"promotion requires a filesystem (or mock) persistence "
                f"root, not {self.kind!r}")
        self.read_only = False
        if self.kind == "filesystem":
            os.makedirs(os.path.join(self.root, "streams"), exist_ok=True)
        # hydration-time caches were taken when this driver opened the
        # root read-only; the dead primary kept writing since
        self._record_cache.clear()
        self._restore_time = None
        self._snapshot_probed = False
        self._loaded_snapshot = None
        max_tick = self.restore_time()  # BEFORE the cut: torn ticks too
        epoch = self.claim_epoch(holder, min_epoch)
        dropped = 0
        for sid in self.list_source_ids():
            log = self._log_for(sid)
            if hasattr(log, "truncate_after"):
                dropped += log.truncate_after(complete_tick)
            log.close()
        if dropped:
            logger.warning(
                "promotion to epoch %d dropped %d entry(ies) of the dead "
                "primary's incomplete final commit (records past tick "
                "%d) — none were acknowledged-complete ticks", epoch,
                dropped, complete_tick)
            self._record_cache.clear()
            self._restore_time = None
        return max_tick, epoch

    # -- identity ----------------------------------------------------------
    def _source_id(self, datasource) -> str:
        return source_id(datasource)

    def _log_for(self, source_id: str):
        if self.kind == "mock":
            log = MockLog(self._backend._mock_store, source_id)
        elif self._s3 is not None:
            log = S3SnapshotLog(self._s3, self.root, source_id)
        else:
            log = SnapshotLog(os.path.join(self.root, "streams",
                                           source_id + ".snap"))
        return _ReadOnlyLog(log) if self.read_only else log

    def stream_path(self, source_id: str) -> str | None:
        """Filesystem path of a source's WAL (None on non-file backends)
        — the byte-level tail surface engine/replica.py polls."""
        if self.kind != "filesystem":
            return None
        return os.path.join(self.root, "streams", source_id + ".snap")

    def oldest_snapshot_tick(self) -> int | None:
        """Tick of the OLDEST retained snapshot generation (None when no
        generation exists). Compaction truncates every WAL to the suffix
        past exactly this tick, so it is the floor of what the log still
        contains — a replica whose applied tick is below it after a
        compaction rescan has provably missed records
        (engine/replica.py)."""
        metas = self._list_generations()
        if not metas:
            return None
        return min(int(m.get("tick", 0)) for m in metas)

    def list_source_ids(self) -> list[str]:
        """Every source id with a durable log under this root (the
        replica's tail set: a source whose id appears here is hydrated
        and tailed from the primary's WAL instead of read live)."""
        if self.kind == "mock":
            return sorted(self._backend._mock_store.keys())
        if self._s3 is not None:
            prefix = "/".join(p for p in (self.root.strip("/"), "streams")
                              if p) + "/"
            return sorted({
                obj["key"][len(prefix):].split("/", 1)[0]
                for obj in self._s3.list_objects(prefix)})
        streams = os.path.join(self.root, "streams")
        if not os.path.isdir(streams):
            return []
        return sorted(f[:-5] for f in os.listdir(streams)
                      if f.endswith(".snap"))

    # -- per-source resume frontier (manifest payload) ---------------------
    def _frontier(self, sid: str) -> dict:
        fr = self._frontiers.get(sid)
        if fr is None:
            fr = self._frontiers[sid] = {
                "entries": 0,   # durable entries, any diff (skip counter)
                "inserts": 0,   # durable insertions (fs key-seq counter)
                "files": {},    # fkey -> [mtime, rows, saw_last]
                "parts": {},    # partition -> max offset (antichain)
            }
        return fr

    @staticmethod
    def _frontier_fold(fr: dict, entries: list) -> None:
        """Fold durable entries' offset labels into the compact frontier —
        the summary the snapshot manifest stores so seek-capable sources
        can continue past a prefix whose WAL records were compacted."""
        files, parts = fr["files"], fr["parts"]
        for entry in entries:
            fr["entries"] += 1
            if entry[2] > 0:
                fr["inserts"] += 1
            offset = entry[3] if len(entry) > 3 else None
            if not isinstance(offset, tuple):
                continue
            if len(offset) == 3 and offset[0] == "part":
                _kind, p, o = offset
                cur = parts.get(p)
                if cur is None or o > cur:
                    parts[p] = o
            elif len(offset) == 5:
                kind, fkey, mtime, idx, is_last = offset
                fkey = str(fkey)
                if kind == "retract":
                    # the file changed and its old rows were retracted:
                    # forget the stale position (new rows re-populate)
                    files.pop(fkey, None)
                    continue
                st = files.get(fkey)
                if st is None or st[0] != mtime:
                    st = files[fkey] = [mtime, 0, False]
                st[1] = max(st[1], idx + 1)
                st[2] = bool(st[2] or is_last)

    # -- operator-state snapshots ------------------------------------------
    def _list_generations(self) -> list[dict]:
        """Manifest dicts of every on-disk generation, newest first. A
        manifest that fails to parse is skipped (and logged): the
        generation's state file without its manifest is an orphan from a
        crash mid-write, never a valid snapshot."""
        metas: list[dict] = []
        if self.kind == "mock":
            metas = list(getattr(self._backend, "_mock_snapshots", []))
        elif self._snap_dir and os.path.isdir(self._snap_dir):
            import json

            for fname in os.listdir(self._snap_dir):
                if not fname.endswith(".json"):
                    continue
                path = os.path.join(self._snap_dir, fname)
                try:
                    with open(path) as f:
                        meta = json.load(f)
                    meta["_manifest_path"] = path
                    metas.append(meta)
                except Exception as e:
                    logger.error(
                        "unreadable snapshot manifest %s (%s: %s) — "
                        "skipping that generation", path,
                        type(e).__name__, e)
        return sorted(metas, key=lambda m: m.get("generation", 0),
                      reverse=True)

    def _read_state_blob(self, meta: dict) -> bytes:
        if self.kind == "mock":
            data = meta["state"]
        else:
            with open(os.path.join(self._snap_dir,
                                   meta["state_file"]), "rb") as f:
                data = f.read()
        if not data.startswith(_STATE_MAGIC):
            raise ValueError("state file missing magic header")
        blob = data[len(_STATE_MAGIC):]
        if len(blob) != int(meta["state_bytes"]) \
                or zlib.crc32(blob) != int(meta["state_crc32"]):
            raise ValueError("state checksum mismatch (corrupt snapshot)")
        return blob

    def load_snapshot(self) -> dict | None:
        """Newest VALID snapshot generation (checksum-verified, decoded by
        the restricted unpickler), or None. A corrupt newest generation
        falls back one generation, loudly — the WAL keeps the suffix back
        to the oldest RETAINED generation, so the fallback replays more
        but recovers byte-identically."""
        if self._snapshot_probed:
            return self._loaded_snapshot
        self._snapshot_probed = True
        if not self.snapshots_supported or not _restore_enabled():
            return None
        for meta in self._list_generations():
            gen = int(meta.get("generation", 0))
            try:
                blob = self._read_state_blob(meta)
                payload = _safe_loads(blob)
            except Exception as e:
                logger.error(
                    "snapshot generation %d unreadable (%s: %s) — "
                    "falling back one generation", gen,
                    type(e).__name__, e)
                self._corrupt_gens.add(gen)
                continue
            self._validated_gens.add(gen)
            tick = int(meta["snapshot_tick"])
            self._loaded_snapshot = {
                "generation": gen, "tick": tick, "payload": payload,
                "sources": meta.get("sources") or {}}
            self.last_snapshot_tick = tick
            self.snapshot_generation = gen
            self.snapshot_bytes = len(blob)
            logger.info(
                "restored operator-state snapshot generation %d "
                "(tick %d, %d bytes) — replaying only the WAL suffix",
                gen, tick, len(blob))
            return self._loaded_snapshot
        return None

    def write_snapshot(self, tick: int, payload_obj) -> bool:
        """Durably record an operator-state snapshot at ``tick`` (all
        entries sealed <= tick are already committed by the caller), then
        compact: truncate each source's WAL to the suffix past the oldest
        RETAINED generation's tick and prune old generations. Write
        order — state file, then manifest (each atomic: tmp + fsync +
        rename), then truncation — makes every crash point safe: before
        the manifest, the generation does not exist; after it, covered
        WAL records are ignored on replay whether or not the truncation
        ran."""
        if self.read_only:
            raise ReadOnlyPersistenceError(
                "write_snapshot() on a read-only persistence root — a "
                "replica must never write snapshot generations")
        self.check_fenced("write_snapshot()")
        if not self.snapshots_supported:
            if not self._snapshot_warned:
                self._snapshot_warned = True
                logger.warning(
                    "operator-state snapshots are not supported on the "
                    "%r persistence backend — recovery stays full-WAL "
                    "replay (restart cost grows with history)", self.kind)
            return False
        if tick <= self.last_snapshot_tick:
            return False  # watermark did not advance: no empty churn
        blob = pickle.dumps(payload_obj, protocol=pickle.HIGHEST_PROTOCOL)
        # write-time proof the restricted unpickler accepts this snapshot:
        # a checkpoint that cannot load must never truncate the WAL
        _safe_loads(blob)
        existing = self._list_generations()
        gen = (int(existing[0].get("generation", 0)) + 1) if existing \
            else self.snapshot_generation + 1
        sources = {
            sid: {"covered": fr["entries"], "inserts": fr["inserts"],
                  "files": fr["files"],
                  "parts": [[p, o] for p, o in fr["parts"].items()]}
            for sid, fr in self._frontiers.items()}
        faults.hit("persistence.snapshot.write", tick=tick, generation=gen)
        meta = {"format": "pwsnapmeta1", "generation": gen,
                "snapshot_tick": tick, "state_bytes": len(blob),
                "state_crc32": zlib.crc32(blob), "sources": sources,
                "epoch": self.fencing_epoch,
                "wrote_at": _time.time()}
        if self.kind == "mock":
            meta["state"] = _STATE_MAGIC + blob
            snaps = getattr(self._backend, "_mock_snapshots", None)
            if snaps is None:
                snaps = self._backend._mock_snapshots = []
            snaps.append(meta)
        else:
            os.makedirs(self._snap_dir, exist_ok=True)
            state_file = f"{gen:08d}.state"
            meta["state_file"] = state_file
            with blocking_call("persistence.snapshot.write"):
                _atomic_write_bytes(
                    os.path.join(self._snap_dir, state_file),
                    _STATE_MAGIC + blob)
                from pathway_tpu.engine.flight_recorder import \
                    atomic_write_json

                atomic_write_json(
                    os.path.join(self._snap_dir, f"{gen:08d}.json"), meta)
        self.snapshot_generation = gen
        self.last_snapshot_tick = tick
        self.snapshots_total += 1
        self.snapshot_bytes = len(blob)
        self.wal_bytes_since_snapshot = 0
        self.wal_entries_uncovered = 0
        # every durable entry now sits in a record <= tick: a normal-path
        # restart replays nothing (records physically retained for the
        # generation-fallback window are filtered by the snapshot tick)
        self.wal_replayable_entries = 0
        self._validated_gens.add(gen)
        self._compact()
        return True

    def _gen_valid(self, meta: dict) -> bool:
        """Checksum-verify a generation at most once (this driver's own
        writes and load-time passes are pre-validated)."""
        gen = int(meta.get("generation", 0))
        if gen in self._validated_gens:
            return True
        if gen in self._corrupt_gens:
            return False
        try:
            self._read_state_blob(meta)
        except Exception as e:
            logger.error(
                "snapshot generation %d is corrupt (%s: %s) — excluded "
                "from retention (it must not shadow a valid fallback)",
                gen, type(e).__name__, e)
            self._corrupt_gens.add(gen)
            return False
        self._validated_gens.add(gen)
        return True

    def _compact(self) -> None:
        """Truncate WAL prefixes covered by the oldest retained VALID
        generation and prune everything else — corrupt generations never
        occupy a retention slot (keeping one would prune the real
        fallback and truncate the WAL to a tick only the corrupt
        generation covers). Runs strictly after the new generation is
        durable; a crash at any point here only costs replay time, never
        data."""
        if self.read_only:
            raise ReadOnlyPersistenceError(
                "_compact() on a read-only persistence root — a replica "
                "must never truncate the primary's WAL or prune its "
                "snapshot generations")
        gens = self._list_generations()
        valid = [m for m in gens if self._gen_valid(m)]
        kept = valid[:_keep_generations()]
        kept_ids = {id(m) for m in kept}
        if _compact_enabled() and kept:
            truncate_tick = int(kept[-1]["snapshot_tick"])
            faults.hit("persistence.compact.truncate", tick=truncate_tick)
            dropped_entries = 0
            for _sid, log, _rec in self._sessions:
                if hasattr(log, "truncate_to"):
                    dropped_entries += log.truncate_to(truncate_tick)
            if dropped_entries:
                self.compactions_total += 1
        for meta in gens:
            if id(meta) not in kept_ids:
                self._delete_generation(meta)

    def _delete_generation(self, meta: dict) -> None:
        if self.kind == "mock":
            try:
                self._backend._mock_snapshots.remove(meta)
            except ValueError:
                pass
            return
        # manifest first: a state file without a manifest is an inert
        # orphan, while a manifest without its state would be a loud
        # (checksum-failing) fallback on every restart
        for path in (meta.get("_manifest_path"),
                     os.path.join(self._snap_dir,
                                  meta.get("state_file", ""))
                     if meta.get("state_file") else None):
            if path:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- runtime API (called by StreamingRuntime) --------------------------
    def _records(self, sid: str) -> list:
        """Read (and cache) a source's log records — restore_time and
        attach_source both need them; unpickle only once per startup."""
        if sid not in self._record_cache:
            self._record_cache[sid] = self._log_for(sid).read_all()
        return self._record_cache[sid]

    def restore_time(self) -> int:
        """Last committed logical time across all logged sources (0 = fresh)."""
        if self._restore_time is not None:
            return self._restore_time
        snap = self.load_snapshot()
        last = snap["tick"] if snap is not None else 0
        for sid in self.list_source_ids():
            for rec in self._records(sid):
                last = max(last, rec[0])
        self._restore_time = last
        return last

    def attach_source(self, datasource, session, replay: bool = True):
        """Replay this source's durable prefix into ``session`` and return
        the recording proxy the live reader thread must push into.

        Two continuation protocols (reference: connectors/mod.rs:215-368 —
        ``rewind_from_disk_snapshot`` then continue from stored offsets):

        - **seekable** sources (define ``seek(replayed_entries)``) receive
          every replayed ``(key, row, diff, offset)`` and position their
          reader past the durable prefix themselves; nothing live is
          dropped. This is exact under reordering and file mutation.
        - otherwise the source is assumed to re-emit the identical entry
          sequence on restart, and the first N live pushes are dropped.

        ``replay=False`` — the promotion path (engine/streaming.py): the
        promoting replica's scheduler already holds the durable state
        (it tailed every complete tick), so nothing is pushed; only the
        resume frontier, the seek protocol and the skip counter are set
        up exactly as a restart would, so the new primary's readers
        continue past the durable prefix without double-applying it.
        """
        if self.read_only:
            raise ReadOnlyPersistenceError(
                "attach_source() on a read-only persistence root — a "
                "replica hydrates through engine/replica.py (tail-only), "
                "never through the recording/commit path")
        sid = self._source_id(datasource)
        if sid in self._attached_ids:
            raise ValueError(
                f"two persisted sources share the id {sid!r} — their snapshot "
                "logs would cross-replay into each other's tables. Give each "
                "connector a unique persistent_id.")
        self._attached_ids.add(sid)
        log = self._log_for(sid)
        snap = self.load_snapshot()
        snap_tick = snap["tick"] if snap is not None else 0
        src_meta = (snap["sources"].get(sid)
                    if snap is not None else None) or {}
        covered = int(src_meta.get("covered", 0))
        records = self._records(sid)
        if snap_tick:
            # records <= the snapshot tick are covered by restored
            # operator state. A crash between snapshot-durable and
            # WAL-truncate leaves them in the log — they are ignored
            # here, never replayed on top of the state that already
            # includes them.
            records = [r for r in records if r[0] > snap_tick]
        replayed: list = []
        for rec in records:
            for entry in rec[1]:
                key, row, diff = entry[0], entry[1], entry[2]
                offset = entry[3] if len(entry) > 3 else None
                if replay:
                    session.push(key, row, diff)
                replayed.append((key, row, diff, offset))
        self.wal_replayable_entries += len(replayed)
        self.wal_entries_uncovered += len(replayed)
        # resume frontier: continue from the manifest's compact summary,
        # then fold the replayed WAL suffix on top
        fr = self._frontier(sid)
        if src_meta:
            fr["entries"] = covered
            fr["inserts"] = int(src_meta.get("inserts", 0))
            fr["files"] = {k: list(v)
                           for k, v in (src_meta.get("files") or {}).items()}
            fr["parts"] = {p: o for p, o in (src_meta.get("parts") or [])}
        self._frontier_fold(fr, replayed)
        from pathway_tpu.engine.offsets import OffsetAntichain

        antichain = OffsetAntichain(fr["parts"]) if fr["parts"] else None
        if antichain and hasattr(datasource, "seek_offsets"):
            # partitioned source: continue each partition past its durable
            # frontier (reference OffsetAntichain, persistence/frontier.rs)
            datasource.seek_offsets(antichain)
            skip = 0
        elif covered and hasattr(datasource, "seek_snapshot"):
            # the prefix was compacted away: hand the source the MANIFEST
            # frontier (per-file positions, insert count) plus the raw
            # WAL suffix — it positions its reader without the entries
            datasource.seek_snapshot(
                {"files": fr["files"], "inserts": fr["inserts"]}, replayed)
            skip = 0
        elif hasattr(datasource, "seek") and not covered:
            datasource.seek(replayed)
            skip = 0
        else:
            if covered and hasattr(datasource, "seek"):
                import logging

                logging.getLogger(__name__).warning(
                    "source %r defines seek() but not seek_snapshot(); "
                    "its replay prefix was compacted by an operator-state "
                    "snapshot, so resume falls back to the prefix-skip "
                    "protocol (the reader is assumed to re-emit the "
                    "identical first %d entries).", sid,
                    covered + len(replayed))
            elif replayed or covered:
                import logging

                logging.getLogger(__name__).warning(
                    "resuming source %r with the prefix-replay protocol: the "
                    "reader is assumed to re-emit the identical first %d "
                    "entries on restart. Sources that re-read *current* "
                    "state (databases, compacted topics) need a seek() "
                    "implementation for exact resume.", sid,
                    covered + len(replayed))
            skip = covered + len(replayed)
        rec = _RecordingSession(session, skip=skip)
        self._sessions.append((sid, log, rec))
        return rec

    def seal(self, tick: int) -> None:
        """Stamp a durability seal on every recorded source (streaming
        loop, right before the tick's drain)."""
        for _sid, _log, rec in self._sessions:
            rec.seal(tick)

    def commit(self, time: int, watermark: int | None = None,
               inflight: int = 0) -> None:
        """Durably record entries whose processing is provably complete.

        ``watermark=None`` — synchronous callers and the end-of-stream
        flush: everything pushed so far is sealed at ``time`` and
        committed (the caller holds hard-barrier semantics: ``time`` is
        fully processed when this runs).

        With a watermark — the pipelined streaming loop: only entries
        sealed at ticks <= ``watermark`` (the device bridge's resolved
        prefix) are appended, in a record carrying the *watermark* tick.
        Either way the log invariant is the same: a record's presence
        implies its time was fully processed — now held exactly, at any
        in-flight depth, instead of by draining the bridge first.
        Transient backend write failures retry inside the log's append
        (``_retrying_write``)."""
        if self.read_only:
            raise ReadOnlyPersistenceError(
                "commit() on a read-only persistence root — a replica "
                "must never append to the primary's WAL")
        self.check_fenced("commit()")
        t0 = _time.perf_counter()
        if watermark is None:
            watermark = time
            self.seal(time)
        # fault point between reading the watermark and the durable
        # append: a crash here loses nothing (the sealed entries are
        # re-emitted by the reader on restart, never skipped)
        faults.hit("persistence.commit", time=time, watermark=watermark)
        wrote = False
        for sid, log, rec in self._sessions:
            entries = rec.take_sealed(watermark)
            if entries:
                nbytes = log.append(watermark, entries,
                                    self.fencing_epoch) or 0
                self.entries_committed += len(entries)
                self.wal_replayable_entries += len(entries)
                self.wal_entries_uncovered += len(entries)
                self.wal_bytes_since_snapshot += nbytes
                self._frontier_fold(self._frontier(sid), entries)
                wrote = True
        self.commits += 1
        self.last_commit_tick = max(self.last_commit_tick, time)
        self.last_commit_watermark = max(self.last_commit_watermark,
                                         watermark)
        self.last_inflight_at_commit = inflight
        if wrote:
            self.commits_with_data += 1
            self.commit_wait.observe((_time.perf_counter() - t0) * 1e3)

    def stats(self) -> dict:
        """Commit-watermark snapshot for /status and the dashboard."""
        return {
            "commits": self.commits,
            "commits_with_data": self.commits_with_data,
            "entries_committed": self.entries_committed,
            "watermark": self.last_commit_watermark,
            "lag_ticks": max(0, self.last_commit_tick
                             - self.last_commit_watermark),
            "inflight_at_commit": self.last_inflight_at_commit,
            "write_retries": write_retries_total(),
            "commit_wait_ms_sum": round(self.commit_wait.sum_ms, 3),
            "commit_wait_count": self.commit_wait.count,
            # -- snapshot / compaction tier --------------------------------
            "snapshot_tick": self.last_snapshot_tick,
            "snapshot_generation": self.snapshot_generation,
            "snapshots_total": self.snapshots_total,
            "snapshot_bytes": self.snapshot_bytes,
            "snapshot_age_ticks": max(0, self.last_commit_tick
                                      - self.last_snapshot_tick),
            "compactions_total": self.compactions_total,
            "wal_replayable_entries": self.wal_replayable_entries,
            # -- write-path failover fencing -------------------------------
            "fencing_epoch": self.fencing_epoch,
            "fenced_writes": self.fenced_writes,
        }

    def close(self) -> None:
        for _sid, log, _rec in self._sessions:
            log.close()
