"""Realtime microbatch runtime.

Replaces the reference's worker hot loop (dataflow.rs:5519-5572 —
``loop { probers; flushers; pollers; step_or_park }``): connector threads
feed sessions; every autocommit interval the runtime drains all sessions,
advances the logical timestamp, and runs one scheduler step. Totally-ordered
timestamps + whole-batch steps give the same consistency guarantee as
timely's progress frontiers (every time is complete when processed).
"""

from __future__ import annotations

import threading
import time as _time

from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.graph import Scheduler
from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor


class StreamingRuntime:
    def __init__(self, runner, *, monitoring_level=None, with_http_server=False,
                 persistence_config=None, terminate_on_error=True,
                 default_commit_ms: int = 100, n_workers: int | None = None):
        from pathway_tpu.io._datasource import Session

        if n_workers is None:
            from pathway_tpu.internals.config import get_pathway_config

            n_workers = get_pathway_config().threads
        self.runner = runner
        self.scheduler = Scheduler(runner.graph, n_workers=n_workers)
        self.sessions = []
        self.threads = []
        self.default_commit_ms = default_commit_ms
        self._stop = threading.Event()
        self.monitor = StatsMonitor(monitoring_level or MonitoringLevel.NONE)
        self.persistence = None
        if persistence_config is not None and persistence_config.backend is not None:
            from pathway_tpu.engine.persistence import PersistenceDriver

            self.persistence = PersistenceDriver(persistence_config)
        self.http_server = None
        if with_http_server:
            from pathway_tpu.engine.http_server import MonitoringHttpServer

            self.http_server = MonitoringHttpServer(self)

        for node, datasource in runner._stream_subjects:
            session = Session()
            self.sessions.append((node, session, datasource))

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        time_counter = 1
        if self.persistence is not None:
            time_counter = self.persistence.restore_time() + 1
        replay_only = (
            self.persistence is not None
            and not getattr(self.persistence.config, "continue_after_replay",
                            True))
        for node, session, datasource in self.sessions:
            live_session = session
            if self.persistence is not None:
                # replay the durable prefix into `session`, then hand the
                # reader a recording proxy that skips the replayed count
                live_session = self.persistence.attach_source(datasource, session)
            if replay_only:
                # pure replay (CLI `replay` without --continue): process the
                # recorded prefix only — no live reader threads
                session.close()
            else:
                self.threads.append(datasource.start(live_session))
        if self.http_server is not None:
            self.http_server.start()

        # feed static tables at startup: dimension data (markdown tables,
        # static csv) joined against live streams must be present from tick
        # one. One tick per distinct logical time, like run_batch — a
        # single collapsed batch would net out add/retract pairs that
        # legitimately exist at different times (update streams).
        static_times = sorted({t for _n, feed in self.runner._static_feeds
                               for (t, _k, _r, _d) in feed})
        for t in static_times:
            any_batch = False
            for node, feed in self.runner._static_feeds:
                batch = Delta([(k, r, d) for (ft, k, r, d) in feed
                               if ft == t])
                if batch:
                    self.scheduler.push_source(node, batch)
                    any_batch = True
            if any_batch:
                self.scheduler.run_time(time_counter)
                time_counter += 1

        commit_s = min(
            [s[2].autocommit_duration_ms or self.default_commit_ms
             for s in self.sessions] + [self.default_commit_ms]
        ) / 1000.0

        try:
            while not self._stop.is_set():
                _time.sleep(commit_s)
                any_data = False
                all_closed = True
                for node, session, datasource in self.sessions:
                    entries = session.drain()
                    if entries:
                        any_data = True
                        self.scheduler.push_source(node, Delta(entries))
                    if not session.closed.is_set():
                        all_closed = False
                self.scheduler.run_time(time_counter)
                self.monitor.update(self.scheduler, self.runner.graph,
                                    time_counter)
                if self.persistence is not None:
                    self.persistence.commit(time_counter)
                time_counter += 1
                if all_closed and not any_data:
                    # re-drain: a source may have pushed between its drain()
                    # and closing — loop until truly empty, then final tick
                    leftovers = True
                    while leftovers:
                        leftovers = False
                        for node, session, datasource in self.sessions:
                            entries = session.drain()
                            if entries:
                                leftovers = True
                                self.scheduler.push_source(node, Delta(entries))
                        if leftovers:
                            self.scheduler.run_time(time_counter)
                            time_counter += 1
                    # all sources closed: end-of-stream flush tick
                    self.scheduler.run_time(time_counter, flush=True)
                    if self.persistence is not None:
                        self.persistence.commit(time_counter)
                    break
        finally:
            self.monitor.close()
            self.scheduler.close()
            if self.persistence is not None:
                self.persistence.close()
            if self.http_server is not None:
                self.http_server.stop()
