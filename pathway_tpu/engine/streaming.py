"""Realtime microbatch runtime.

Replaces the reference's worker hot loop (dataflow.rs:5519-5572 —
``loop { probers; flushers; pollers; step_or_park }``): connector threads
feed sessions; every autocommit interval the runtime drains all sessions,
advances the logical timestamp, and runs one scheduler step. Totally-ordered
timestamps + whole-batch steps give the same consistency guarantee as
timely's progress frontiers (every time is complete when processed).
"""

from __future__ import annotations

import threading
import time as _time

import weakref

from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.graph import Scheduler
from pathway_tpu.internals.monitoring import MonitoringLevel, StatsMonitor
from pathway_tpu.testing import faults

# live runtimes (weak: a runtime dies with its last strong ref). Lets
# embedding code — and the test harness — stop pw.run() loops started on
# background threads: stop_all() requests stop and joins reader threads.
_ACTIVE_RUNTIMES: "weakref.WeakSet[StreamingRuntime]" = weakref.WeakSet()


def stop_all(join_timeout: float = 5.0) -> None:
    """Request stop on every live StreamingRuntime and join their reader
    threads; also stops static-mode connectors sleeping between polls
    (CollectSession). Safe to call from any thread; idempotent."""
    from pathway_tpu.io._datasource import stop_collect_sessions

    stop_collect_sessions()
    for rt in list(_ACTIVE_RUNTIMES):
        rt.stop()
    for rt in list(_ACTIVE_RUNTIMES):
        rt.join_readers(join_timeout)


class StreamingRuntime:
    def __init__(self, runner, *, monitoring_level=None, with_http_server=False,
                 persistence_config=None, terminate_on_error=True,
                 default_commit_ms: int = 100, n_workers: int | None = None,
                 cluster=None, connector_policy=None, watchdog=None,
                 trace_path: str | None = None, replica=None, qos=None):
        from pathway_tpu.engine.supervisor import ConnectorSupervisor
        from pathway_tpu.engine.threads import install_excepthook
        from pathway_tpu.io._datasource import Session

        # read-replica mode (engine/replica.py): hydrate from the
        # primary's snapshot + WAL suffix through a READ-ONLY driver and
        # tail the durability log instead of reading persisted feeds
        # live; serving sources (rest routes) still run. Mutually
        # exclusive with owning the persistence root or clustering.
        self.replica = replica
        if replica is not None:
            if persistence_config is not None:
                raise ValueError(
                    "a replica cannot own a persistence root: it tails "
                    "the PRIMARY's root read-only (drop "
                    "persistence_config, or drop replica_of)")
            if cluster is not None:
                raise ValueError(
                    "replica mode is single-process (scale out by adding "
                    "replicas behind the router, not cluster workers)")
        self.role = "replica" if replica is not None else "primary"

        # uncaught exceptions in ANY engine thread land in the ErrorLog
        # and flip /healthz instead of dying silently on stderr
        install_excepthook()

        if n_workers is None:
            from pathway_tpu.internals.config import get_pathway_config

            n_workers = get_pathway_config().threads
        self.runner = runner
        self.cluster = cluster
        self.default_commit_ms = default_commit_ms
        self.terminate_on_error = terminate_on_error
        self._stop = threading.Event()
        # last tick run_time RETURNED for (pipelined: its device leg may
        # still be in flight — the bridge watermark, not this counter, is
        # the durability frontier)
        self._last_completed_tick = 0
        # an engine failure swallowed by the degrade path
        # (terminate_on_error=False): kept so teardown neither re-raises
        # it nor mistakes it for an unobserved device error
        self._degraded_engine_error = None
        self.monitor = StatsMonitor(monitoring_level or MonitoringLevel.NONE)
        # QoS control plane (engine/qos.py): resolved FIRST because an
        # armed controller needs the measurement plane — QoS implies the
        # flight recorder (and with it the request tracker)
        from pathway_tpu.engine.qos import resolve_qos

        self._qos_config = resolve_qos(qos)
        if self._qos_config is not None and cluster is not None:
            raise ValueError(
                "QoS is single-process (the controller partitions ONE "
                "device's time; scale out with replicas behind the "
                "router, each running its own controller)")
        self.qos = None
        # flight recorder (engine/flight_recorder.py): on when a trace
        # path is configured or the data is observable (http server /
        # live dashboard), or when QoS needs the request tracker;
        # otherwise None — one dead branch per op step
        from pathway_tpu.engine.flight_recorder import FlightRecorder

        self.recorder = FlightRecorder.from_env(
            trace_path=trace_path,
            auto_on=(with_http_server or self.monitor.enabled()
                     or self._qos_config is not None))
        if self.recorder is not None:
            # fleet identity on the trace (engine/fleet_observability.py):
            # the merged Perfetto timeline names each process's track by
            # role + process label, and replicas share the id the router
            # knows them by
            import os as _os

            self.recorder.role = self.role
            self.recorder.process = (
                replica.replica_id if replica is not None
                else _os.environ.get("PATHWAY_REPLICA_ID")
                or f"primary-{_os.getpid()}")
        # continuous profiler (engine/profiler.py): same observability
        # arming rule as the recorder; installed process-wide so the
        # kernel cost-model hooks and the bridge's leg context find it
        # with one global load. Sampling starts at run().
        from pathway_tpu.engine.profiler import (Profiler, current_profiler,
                                                 install_profiler)

        self.profiler = Profiler.from_env(
            auto_on=(with_http_server or self.monitor.enabled()
                     or self._qos_config is not None))
        self._installed_profiler = False
        if self.profiler is not None and current_profiler() is None:
            install_profiler(self.profiler)
            self._installed_profiler = True
        self.scheduler = Scheduler(runner.graph, n_workers=n_workers,
                                   cluster=cluster, recorder=self.recorder)
        # watchdog progress on every resolved device leg: the commit loop
        # may legitimately block in submit() behind a full in-flight
        # window — a slow-but-ADVANCING watermark is progress, not a
        # stall; only a frozen one may breach the tick deadline
        self.scheduler.set_watermark_listener(self._on_watermark_advance)
        self.sessions = []
        # supervision: reader threads are owned by the supervisor, which
        # restarts crashed readers per policy and escalates per
        # terminate_on_error (engine/supervisor.py)
        self.supervisor = ConnectorSupervisor(
            terminate_on_error=terminate_on_error,
            default_policy=connector_policy)
        self.supervisor.recorder = self.recorder
        self.monitor.set_supervisor(self.supervisor)
        self.watchdog_config = watchdog
        self.watchdog = None
        # stamped by the commit loop each iteration; the watchdog measures
        # tick progress against this
        self.last_tick_at = _time.monotonic()
        self.persistence = None
        # operator-state snapshot cadence (0 = disabled): env knobs win
        # over the Config fields; single-process only (a cluster's state
        # is split across processes — no consistent single-file cut yet)
        self._snapshot_every_ticks = 0
        self._snapshot_every_bytes = 0
        if persistence_config is not None and persistence_config.backend is not None:
            from pathway_tpu.engine.persistence import PersistenceDriver

            self.persistence = PersistenceDriver(persistence_config)
            # dashboard durability panel: watermark lag is visible live
            self.monitor.persistence = self.persistence
            if cluster is None:
                from pathway_tpu.internals.config import _env_int

                self._snapshot_every_ticks = max(0, _env_int(
                    "PATHWAY_SNAPSHOT_EVERY_TICKS",
                    int(getattr(persistence_config, "snapshot_every_ticks",
                                0) or 0)))
                self._snapshot_every_bytes = max(0, _env_int(
                    "PATHWAY_SNAPSHOT_EVERY_BYTES",
                    int(getattr(persistence_config, "snapshot_every_bytes",
                                0) or 0)))
                if self._snapshots_enabled() \
                        and not self.persistence.snapshots_supported:
                    # never run the (expensive) state-capture pass just
                    # to have write_snapshot discard it every cadence
                    import logging

                    logging.getLogger(__name__).warning(
                        "snapshot cadence configured but the %r "
                        "persistence backend cannot store snapshots — "
                        "recovery stays full-WAL replay",
                        self.persistence.kind)
                    self._snapshot_every_ticks = 0
                    self._snapshot_every_bytes = 0
            if self._snapshots_enabled():
                # consolidated emitted-state tracking must be on BEFORE
                # any data flows, so a later snapshot can re-emit the
                # covered prefix's visible state to fresh sinks
                self.scheduler.enable_output_tracking()
        self.http_server = None
        if with_http_server:
            from pathway_tpu.engine.http_server import MonitoringHttpServer

            self.http_server = MonitoringHttpServer(self)

        for node, datasource in runner._stream_subjects:
            session = Session()
            self.sessions.append((node, session, datasource))
            if getattr(datasource, "durable_ack", False) \
                    and self.persistence is None and replica is None:
                # a durable acknowledgement with no WAL to make it
                # durable would hold every response forever — refuse the
                # contradiction loudly instead of hanging clients
                raise ValueError(
                    "rest_connector(durable_ack=True) requires a "
                    "persistence root (the acknowledgement IS the fsync "
                    "of the request's WAL record) — configure "
                    "persistence, or drop durable_ack")
        if self.replica is not None:
            # classify sources: WAL-backed feeds are tailed (no reader
            # thread), serving sources run live
            self.replica.bind(self.sessions)
        # fleet control channel (engine/replica.py): when a router's
        # control address is configured, this process — replica OR a
        # read-serving primary — registers and heartbeats its applied
        # tick / staleness / serving quantiles over the framed HMAC
        # transport
        from pathway_tpu.engine.replica import (ControlClient,
                                                control_address_from_env)

        self._control_client = None
        ctrl_addr = control_address_from_env()
        if ctrl_addr is not None and cluster is None:
            self._control_client = ControlClient(
                self, ctrl_addr, role=self.role,
                replica_id=(self.replica.replica_id
                            if self.replica is not None else None))
        # source index -> persistence recording proxy: the commit loop
        # drains THROUGH the proxy (seal_drain) so seals align exactly
        # with drains — the alignment operator-state snapshots require
        self._drain_proxies: dict[int, object] = {}
        # (ingest_rows, query_rows, deferred) of the latest drain — the
        # QoS feedback loop's per-tick input
        self._last_drain: tuple[int, int, bool] = (0, 0, False)
        # cumulative bridge exec_ms at the last QoS tick (delta = this
        # tick's resolved device time, the cost-model signal)
        self._qos_exec_ms_seen = 0.0
        # write-path failover (engine/replica.py ControlClient): a
        # ("promote", ...) control frame parks its payload here and the
        # COMMIT LOOP executes the promotion synchronously between ticks
        # — never the control thread, because promotion rewires the
        # scheduler's feeding machinery, which only the loop may touch.
        # Event, not a bare bool: set by the control thread, read by the
        # loop (PWT201).
        self._promote_event = threading.Event()
        self._promote_payload: dict = {}
        # session indexes the replica tails instead of reading live —
        # exactly the sources a promotion must start readers for
        self._tailed_sources: list[int] = []
        self.promotions = 0  # completed promotions (→ /metrics)
        self.failover_promotion_s: float | None = None
        # the tick the promoted timeline ends at — rides every heartbeat
        # so the router can re-anchor surviving replicas exactly there
        # (pending ticks PAST it are the dead primary's torn commit)
        self.promotion_tick: int | None = None

        # request-scoped serving tracing (engine/request_tracker.py):
        # sources that declare a request_tracker slot (rest_connector)
        # get the run's tracker, so each query's ingress/queue/host/
        # device/response stages are stamped end to end
        self._request_tracker = (
            self.recorder.requests if self.recorder is not None else None)
        if self._request_tracker is not None:
            for _node, _session, ds in self.sessions:
                if hasattr(ds, "request_tracker"):
                    ds.request_tracker = self._request_tracker
        # QoS controller (engine/qos.py): turns the tracker's burn rate /
        # stage p50s into per-tick ingest budgets, admission decisions
        # and coalescing accounting. Wired into every serving source's
        # admission gate; the commit loop consults it per tick.
        if self._qos_config is not None:
            if self._request_tracker is None:
                # PATHWAY_FLIGHT_RECORDER=0 force-disabled the
                # measurement plane the controller feeds on: refuse the
                # contradictory config loudly rather than run a control
                # loop with no inputs
                raise ValueError(
                    "QoS is enabled but PATHWAY_FLIGHT_RECORDER=0 "
                    "force-disabled the flight recorder — the controller "
                    "needs the request tracker's burn rate; drop one of "
                    "the two flags")
            from pathway_tpu.engine.qos import (QosController,
                                                install_controller)

            self.qos = QosController(self._qos_config,
                                     self._request_tracker)
            self.supervisor.backpressure_factor = \
                self._qos_config.backpressure_factor
            for _node, _session, ds in self.sessions:
                if hasattr(ds, "qos"):
                    ds.qos = self.qos
            install_controller(self.qos)

    def stop(self) -> None:
        self._stop.set()
        self.supervisor.request_stop()
        for _node, session, _ds in self.sessions:
            session.stopping.set()

    def request_promotion(self, payload: dict | None) -> None:
        """Control-thread entry: ask the commit loop to promote this
        replica to primary. Idempotent — a duplicate frame, or one
        delivered to a process that is already primary, is a no-op when
        the loop picks it up."""
        self._promote_payload = dict(payload or {})
        self._promote_event.set()

    def _execute_promotion(self, time_counter: int) -> int:
        """Promote this replica to primary (commit-loop thread only).

        The state machine: (1) **finish tailing** — pump until the WAL
        yields nothing new for more quiet rounds than the tailer's
        newest-tick hold-back, so every COMPLETE commit tick of the dead
        primary is applied; (2) **fence** — bump the fencing epoch and
        truncate the dead primary's incomplete final commit
        (persistence.promote): from here a resumed zombie primary's next
        write raises FencedPrimaryError; (3) **rewire** — the read-only
        driver becomes this runtime's read-write persistence, and
        connector readers start for every previously-tailed source with
        the durable prefix marked already-covered
        (attach_source(replay=False): the scheduler holds that state
        from tailing); (4) **serve** — the role flips to primary and the
        next heartbeat tells the router to send writes here. A crash
        between (2) and (3) — the ``replica.promote.crash`` fault point
        — leaves a bumped epoch and no primary: the router elects the
        next candidate, whose own promote() bumps the epoch again
        (``min_epoch`` keeps the sequence monotone)."""
        self._promote_event.clear()
        payload = self._promote_payload
        if self.replica is None or self.role == "primary":
            return time_counter  # duplicate/stale frame: no-op
        import logging

        t0 = _time.monotonic()
        tailer = self.replica
        # (1) drain the dead primary's WAL to its last complete tick
        quiet = 0
        while quiet < 5:
            before = tailer.applied_tick
            time_counter = tailer.pump(self, time_counter)
            quiet = quiet + 1 if tailer.applied_tick == before else 0
        complete_tick = tailer.applied_tick
        # (2) fence: claim the next epoch (>= the router's announced
        # one), flip the driver read-write, cut the torn tail
        max_tick, epoch = tailer.driver.promote(
            tailer.replica_id, complete_tick,
            min_epoch=int(payload.get("epoch", 0)))
        faults.hit("replica.promote.crash",
                   epoch=epoch, complete_tick=complete_tick)
        # (3) rewire: the tailer's driver IS the new persistence root
        self.persistence = tailer.driver
        self.monitor.persistence = self.persistence
        for i in self._tailed_sources:
            node, session, datasource = self.sessions[i]
            proxy = self.persistence.attach_source(
                datasource, session, replay=False)
            self._drain_proxies[i] = proxy
            self.supervisor.add_source(node, datasource, session, proxy)
        self._tailed_sources = []
        self.supervisor.start_all()  # only the newly-added entries start
        # the tailer must never pump again — it would re-apply this
        # process's OWN commits; its driver lives on as self.persistence
        # (closed once, by teardown's persistence branch)
        self.replica = None
        # (4) serve
        self.role = "primary"
        if self.recorder is not None:
            self.recorder.role = "primary"
            self.recorder.note_promotion(epoch, complete_tick)
        time_counter = max(time_counter, max_tick + 1)
        self.promotions += 1
        self.promotion_tick = complete_tick
        self.failover_promotion_s = _time.monotonic() - t0
        logging.getLogger(__name__).warning(
            "promoted to primary at fencing epoch %d (complete tick %d, "
            "max durable tick %d, %.3fs): accepting writes",
            epoch, complete_tick, max_tick, self.failover_promotion_s)
        return time_counter

    def join_readers(self, timeout: float = 5.0) -> None:
        """Join connector threads after stop(); they observe the session's
        stop event between polls (Session.sleep / stop_requested)."""
        deadline = _time.monotonic() + timeout
        for t in self.supervisor.all_threads():
            t.join(max(0.0, deadline - _time.monotonic()))

    def _on_watermark_advance(self, tick: int) -> None:
        # bridge-worker thread; a bare float store is atomic under the GIL
        self.last_tick_at = _time.monotonic()

    def _handle_engine_failure(self, error: BaseException) -> bool:
        """A failure escaped the commit loop: a poisoned device leg, a
        persistence append whose write retries were exhausted, or an
        operator error. Escalate through the supervisor's existing
        terminate-vs-degrade contract — teardown's final watermark
        commit makes the last fully-resolved prefix durable on both
        branches, so nothing unprocessed can be covered by the log
        either way. Returns True iff the failure is absorbed as a degrade
        (``terminate_on_error=False``): recorded in the global ErrorLog
        (kind="engine"), flagged on the supervisor, run ends cleanly.
        Interrupts and shutdown requests always re-raise."""
        if isinstance(error, (KeyboardInterrupt, SystemExit,
                              GeneratorExit)):
            return False
        if self.terminate_on_error:
            return False
        import logging

        from pathway_tpu.internals.error import global_error_log

        kind = ("device leg"
                if self.scheduler.take_device_error() is error
                else "engine")
        global_error_log().log(
            f"{kind} failed under terminate_on_error=False; stopping "
            f"ingestion after the last committed watermark: "
            f"{type(error).__name__}: {error}",
            operator="engine", kind="engine")
        logging.getLogger(__name__).error(
            "%s failed; degrading to a clean stop "
            "(terminate_on_error=False). Restart resumes from the last "
            "committed watermark.", kind, exc_info=error)
        self.supervisor.engine_failed = True
        self._degraded_engine_error = error
        return True

    def _commit_watermark_tick(self, tick: int) -> None:
        """One trailing checkpoint: commit the longest resolved prefix of
        device legs (<= ``tick``) WITHOUT draining the bridge — the
        pipeline keeps running ahead at full ``PATHWAY_DEVICE_INFLIGHT``
        depth while durability follows the watermark."""
        wm = self.scheduler.commit_watermark(tick)
        bridge = self.scheduler.bridge_stats()
        self.persistence.commit(
            tick, watermark=wm,
            inflight=bridge["depth"] if bridge is not None else 0)
        self._flush_durable_acks(wm)

    def _flush_durable_acks(self, watermark: int) -> None:
        """Release buffered write acknowledgements for ticks the WAL now
        covers (io/http rest_connector ``durable_ack=True``): commit()
        returned, so entries sealed <= ``watermark`` are fsynced — an
        acknowledgement released here survives SIGKILL (replayed on
        restart, tailed by every replica). Runs on the commit-loop
        thread, same as the subscribe callback that buffers."""
        for _node, _session, ds in self.sessions:
            release = getattr(ds, "on_commit_watermark", None)
            if release is not None:
                release(watermark)

    def _qos_tick_feedback(self, tick_ms: float) -> None:
        """Close the loop for one tick: feed the controller what the
        tick actually did (rows drained, host wall time, the device
        time that retired on the bridge since the last tick) and
        propagate deferral backpressure to the connector readers."""
        ingest_rows, query_rows, deferred = self._last_drain
        device_ms = None
        bridge = self.scheduler.bridge_stats()
        if bridge is not None:
            # cumulative resolved-leg exec time: the per-tick delta lags
            # the submitting tick by the in-flight depth, which is fine
            # for an EWMA cost model
            seen = bridge["exec_ms"]
            device_ms = max(0.0, seen - self._qos_exec_ms_seen)
            self._qos_exec_ms_seen = seen
        self.qos.on_tick(ingest_rows=ingest_rows, deferred=deferred,
                         tick_ms=tick_ms, device_ms=device_ms,
                         queries_in_tick=query_rows)
        self.supervisor.apply_backpressure(self.qos.backpressure_active)

    def _snapshots_enabled(self) -> bool:
        return bool(self._snapshot_every_ticks
                    or self._snapshot_every_bytes)

    def _snapshot_due(self, tick: int) -> bool:
        if not self._snapshots_enabled() or self.persistence is None:
            return False
        p = self.persistence
        if p.wal_entries_uncovered == 0:
            # nothing durable beyond the last generation: operator state
            # is unchanged — an idle stream must not churn generations
            return False
        if self._snapshot_every_ticks and \
                tick - p.last_snapshot_tick >= self._snapshot_every_ticks:
            return True
        return bool(self._snapshot_every_bytes
                    and p.wal_bytes_since_snapshot
                    >= self._snapshot_every_bytes)

    def _snapshot_pass(self, tick: int) -> None:
        """Operator-state checkpoint at ``tick``: wait for the bridge
        WATERMARK to reach the tick (never a full barrier — with the host
        thread parked here no later leg exists, so reaching the watermark
        IS a consistent cut at exactly ``tick``), commit everything
        sealed <= tick so the WAL covers the cut, capture operator state,
        write the snapshot generation and compact the WAL. Any failure
        (unsupported operator, unpicklable state) disables snapshots for
        the rest of the run, loudly — recovery falls back to full-WAL
        replay, never to a checkpoint with missing state."""
        from pathway_tpu.engine.operators import SnapshotUnsupported
        from pathway_tpu.engine.snapshot_sanitizer import \
            SnapshotCoverageViolation

        wm = self.scheduler.wait_watermark(tick)  # re-raises leg failures
        if wm < tick:
            return  # frozen/idle bridge: no consistent cut available
        bridge = self.scheduler.bridge_stats()
        self.persistence.commit(
            tick, watermark=tick,
            inflight=bridge["depth"] if bridge is not None else 0)
        if self.persistence.wal_entries_uncovered == 0:
            # the watermark moved but no durable entry lies beyond the
            # last generation (clean shutdown of an idle stream, teardown
            # after a quiescent tail): skip — no empty-generation churn.
            # A pure-replay restart DOES snapshot here: its replayed
            # suffix counts as uncovered, and covering it bounds the
            # NEXT restart.
            return
        try:
            payload = {
                "graph": self.scheduler.graph_fingerprint(),
                "n_workers": self.scheduler.n_workers,
                "nodes": self.scheduler.snapshot_operator_states(),
            }
            self.persistence.write_snapshot(tick, payload)
        except SnapshotUnsupported as e:
            import logging

            logging.getLogger(__name__).warning(
                "operator-state snapshots disabled for this run: %s", e)
            self._snapshot_every_ticks = 0
            self._snapshot_every_bytes = 0
        except faults.InjectedFault:
            # test-injected crash at a snapshot/compaction fault point:
            # die like any other armed point (the crash sweep simulates
            # process death here, not a degradable write failure)
            raise
        except SnapshotCoverageViolation:
            # the sanitizer found a snapshot that would restore wrong —
            # degrading to WAL replay would hide exactly the bug the
            # sanitizer exists to surface; fail the run loudly
            raise
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "operator-state snapshot at tick %d failed; snapshots "
                "disabled for this run (recovery falls back to full-WAL "
                "replay)", tick, exc_info=True)
            self._snapshot_every_ticks = 0
            self._snapshot_every_bytes = 0

    def _restore_snapshot(self) -> int:
        """Load the newest valid snapshot (if any), restore operator
        states and re-emit the covered prefix's consolidated output state
        to the sinks. Returns the snapshot tick (0 = none)."""
        snap = self.persistence.load_snapshot()
        if snap is None:
            return 0
        payload = snap["payload"]
        if payload.get("graph") != self.scheduler.graph_fingerprint():
            raise ValueError(
                "persistence root carries an operator-state snapshot for "
                "a DIFFERENT pipeline (graph fingerprint mismatch) — the "
                "program changed between runs; clear the persistence "
                "root to start fresh")
        self.scheduler.restore_operator_states(payload["nodes"])
        self.scheduler.emit_restored_outputs(snap["tick"])
        return snap["tick"]

    def _drain_and_forward(self, tick: int, budgeted: bool = True):
        """Drain local sessions; under a cluster split each source's rows
        by owning process (single reader on process 0 forwards shards —
        reference: 'single reader forwards for non-partitioned sources').
        Returns (any_data, all_closed, pushes) where pushes maps
        peer -> {source index -> entries}.

        With QoS armed (and ``budgeted``), ingest sources drain at most
        the controller's per-tick row budget (engine/qos.py): clipped
        rows stay *in their session* and ride later ticks through this
        same path, so seals keep covering exactly what each tick drained
        — deferral moves timestamps, never durability or content.
        Serving sources (request-tracking) are never clipped; the
        end-of-stream re-drain passes ``budgeted=False`` (latency has no
        meaning once every source closed — finish at full throughput)."""
        any_data = False
        all_closed = True
        tracker = self._request_tracker
        pushes: dict[int, dict[int, list]] = {}
        qos = self.qos
        budget = (qos.ingest_row_budget()
                  if qos is not None and budgeted else None)
        ingest_rows = 0
        query_rows = 0
        deferred = False
        n = len(self.sessions)
        # rotate the drain order of INGEST sources by tick so a tight
        # budget cannot starve whichever source happens to sit last
        order = list(range(n))
        if budget is not None and n > 1:
            r = tick % n
            order = order[r:] + order[:r]
        for i in order:
            node, session, datasource = self.sessions[i]
            serving = hasattr(datasource, "request_tracker")
            limit = None
            if budget is not None and not serving:
                limit = budget - ingest_rows
                if limit < 0:
                    limit = 0
            rec = self._drain_proxies.get(i)
            # the recording proxy drains + seals atomically: sealed <= t
            # IS drained <= t, the consistency-cut alignment snapshots
            # need (a separate seal would leak gap entries into t+1).
            # pwt-ok: PWT307 — the plain drain() arm only runs when
            # rec is None, i.e. the source is NOT persisted: there is
            # no WAL to seal against, so nothing can be lost on crash
            entries = session.drain(limit) if rec is None \
                else rec.seal_drain(tick, limit)
            if limit is not None and session.backlog() > 0 \
                    and len(entries) >= limit:
                # the budget clipped this source: the remainder rides a
                # later tick (never dropped — visible in the counters)
                deferred = True
                qos.note_deferral(session.backlog())
            if entries:
                any_data = True
                if serving:
                    query_rows += len(entries)
                else:
                    ingest_rows += len(entries)
                if tracker is not None and \
                        getattr(datasource, "request_tracker", None) \
                        is tracker:
                    # tick-pickup stamp: ends each request's queue stage
                    tracker.picked_up(entries, tick)
                delta = Delta(entries)
                if self.cluster is not None:
                    for peer, ents in self.scheduler.partition_remote(
                            delta).items():
                        pushes.setdefault(peer, {})[i] = ents
                self.scheduler.push_source(node, delta)
            if not session.closed.is_set():
                all_closed = False
        self._last_drain = (ingest_rows, query_rows, deferred)
        return any_data, all_closed, pushes

    def _tick_sync(self, tick, any_data, all_closed, pushes):
        """Cluster barrier per commit tick: exchange forwarded source rows
        and merge liveness so all processes tick (and stop) in lockstep."""
        if self.cluster is None:
            return any_data, all_closed
        msgs = {p: {"rows": pushes.get(p), "any": any_data,
                    "closed": all_closed} for p in self.cluster.peers}
        recv = self.cluster.exchange(("tick", tick), msgs)
        for payload in recv.values():
            rows = payload.get("rows")
            if rows:
                for i, ents in rows.items():
                    node = self.sessions[i][0]
                    self.scheduler.push_source(node, Delta(ents))
                    any_data = True
            any_data = any_data or payload["any"]
            all_closed = all_closed and payload["closed"]
        return any_data, all_closed

    def run(self) -> None:
        _ACTIVE_RUNTIMES.add(self)
        time_counter = 1
        restored_tick = 0
        replay_only = (
            self.persistence is not None
            and not getattr(self.persistence.config, "continue_after_replay",
                            True))
        reader_here = self.cluster is None or self.cluster.process_id == 0
        if self.persistence is not None:
            time_counter = self.persistence.restore_time() + 1
            if self.cluster is None:
                # bounded-time recovery: load the newest valid snapshot,
                # restore operator state at its tick and re-emit the
                # covered prefix's consolidated outputs — the WAL suffix
                # (replayed below via attach_source) is all that re-runs
                restored_tick = self._restore_snapshot()
            elif self.persistence.load_snapshot() is not None:
                # a snapshot-compacted root cannot restore under a
                # cluster (state is per-process; attach_source would
                # silently skip the covered records): fail loudly rather
                # than drop the covered prefix
                raise ValueError(
                    "persistence root carries an operator-state snapshot "
                    "but this run is clustered (PATHWAY_PROCESSES > 1) — "
                    "snapshot restore is single-process only. Re-run "
                    "single-process, or set PATHWAY_SNAPSHOT_RESTORE=0 "
                    "(sound only if the WAL was never compacted).")
        if self.replica is not None:
            # hydrate: newest valid snapshot generation -> operator state
            # (KNN re-upload, consolidated sink re-emission); the WAL
            # suffix replays through the first pump rounds below
            restored_tick = self.replica.hydrate(self.scheduler)
            # local ticks start past every tick the primary's root
            # already covers: one monotone clock across restore + tailing
            time_counter = max(restored_tick,
                               self.replica.driver.restore_time()) + 1
        for i, (node, session, datasource) in enumerate(self.sessions):
            live_session = session
            if self.replica is not None and self.replica.is_tailed(i):
                # tailed feed: rows arrive from the primary's WAL — the
                # reader thread must never start (it would double-ingest,
                # and the replica may not even reach the raw inputs).
                # Remembered: a promotion starts exactly these readers.
                self._tailed_sources.append(i)
                continue
            if self.persistence is not None and reader_here:
                # replay the durable prefix into `session`, then hand the
                # reader a recording proxy that skips the replayed count
                live_session = self.persistence.attach_source(datasource, session)
                self._drain_proxies[i] = live_session
            if replay_only or not reader_here:
                # pure replay (CLI `replay` without --continue) or a
                # non-reading cluster process: no live reader threads —
                # process 0 forwards this process's shard every tick
                session.close()
            else:
                self.supervisor.add_source(node, datasource, session,
                                           live_session)
        self.supervisor.start_all()
        if self.http_server is not None:
            self.http_server.start()
        if self.profiler is not None:
            self.profiler.start()

        # feed static tables at startup: dimension data (markdown tables,
        # static csv) joined against live streams must be present from tick
        # one. One tick per distinct logical time, like run_batch — a
        # single collapsed batch would net out add/retract pairs that
        # legitimately exist at different times (update streams). Static
        # feeds are SPMD-identical, so no cluster forwarding is needed.
        # Restored-snapshot runs SKIP them: the restored operator state
        # already includes the static rows (re-pushing would double-count
        # them; same assumption as replay — static inputs are unchanged
        # between runs).
        static_by_time, static_times = self.runner.static_feeds_by_time()
        if restored_tick:
            static_times = []
        for t in sorted(static_times):
            any_batch = False
            for node, groups in static_by_time:
                batch = groups.get(t)
                if batch:
                    self.scheduler.push_source(node, Delta(batch))
                    any_batch = True
            if any_batch:
                self.scheduler.run_time(time_counter)
                time_counter += 1

        commit_s = min(
            [s[2].autocommit_duration_ms or self.default_commit_ms
             for s in self.sessions] + [self.default_commit_ms]
        ) / 1000.0
        if self.replica is not None:
            # the loop cadence is also the WAL poll cadence — staleness
            # is bounded by max(commit interval, PATHWAY_REPLICA_POLL_MS)
            from pathway_tpu.engine.replica import _poll_interval_s

            commit_s = min(commit_s, _poll_interval_s())
        if self.qos is not None:
            # the tick interval IS the device-time budget denominator:
            # a fixed PATHWAY_QOS_QUERY_BUDGET partitions this many ms
            self.qos.tick_interval_ms = max(1.0, commit_s * 1e3)
        if self._control_client is not None:
            self._control_client.start()

        from pathway_tpu.engine.supervisor import Watchdog

        self.watchdog = Watchdog(self, self.supervisor, self.watchdog_config)
        self.watchdog.start()
        # teardown may write a FINAL operator-state snapshot, but only
        # after a clean loop exit: a loop dying mid-commit may have
        # consumed sealed entries (take_sealed) whose append never became
        # durable — a snapshot covering that state would mark them
        # processed while the restart's reader re-emits them (double
        # count). The flag flips only when the while-loop exits normally.
        loop_clean = False
        try:
            # Event wait, not time.sleep: a stop request wakes the loop
            # immediately instead of out-waiting the commit interval
            # (the PWT206 sleep-polling pattern this checker family bans)
            while not self._stop.wait(commit_s):
                self.last_tick_at = _time.monotonic()
                if self._promote_event.is_set():
                    # router-requested failover: runs HERE, synchronously
                    # between ticks, so it can never race a pump or drain
                    time_counter = self._execute_promotion(time_counter)
                # supervision tick: observe crashed/stalled readers, fire
                # scheduled backoff restarts, escalate exhausted retries
                if self.supervisor.poll() is not None:
                    if self.cluster is None:
                        break
                    # under a cluster, breaking out here would strand the
                    # peers mid-exchange (they block in Cluster.exchange
                    # until the recv timeout, then misreport a hung peer).
                    # Instead stop the local readers, close every local
                    # session with the error, and fall through: the normal
                    # tick merge sees all_closed on every process and the
                    # whole cluster leaves through the same lockstep
                    # end-of-stream path; the fatal re-raise below still
                    # fires on this process after teardown.
                    self.supervisor.request_stop()
                    for _node, session, _ds in self.sessions:
                        session.stopping.set()
                        session.close(reason="error",
                                      error=self.supervisor.fatal_error)
                # durability seals ride the drain itself: _drain_and_forward
                # drains each persisted source through its recording proxy's
                # seal_drain(tick), so "sealed at t" == "drained at t" ==
                # "complete once the tick-t leg resolves" holds EXACTLY —
                # required by operator-state snapshots (a seal taken before
                # the drain would let gap entries be processed at t but
                # recorded at t+1, double-counting them after a restore)
                if self.replica is not None:
                    # tail the primary's WAL: every complete new primary
                    # commit tick is applied, coalesced per round into
                    # one local scheduler tick (engine/replica.py pump —
                    # advances applied_tick)
                    time_counter = self.replica.pump(self, time_counter)
                any_data, all_closed, pushes = self._drain_and_forward(
                    time_counter)
                any_data, all_closed = self._tick_sync(
                    time_counter, any_data, all_closed, pushes)
                # under a cluster an idle tick would still pay one TCP
                # round per exchanged node inside run_time; the merged
                # any_data is identical on every process, so skipping is
                # SPMD-consistent (single-process keeps ticking — empty
                # ticks are near-free and drive as-of-now retractions)
                if self.cluster is None or any_data:
                    t_tick0 = (_time.perf_counter()
                               if self.qos is not None else 0.0)
                    self.scheduler.run_time(time_counter)
                    # stamp after the step too: a long (healthy) batch
                    # counts as progress the moment it completes, so only
                    # a single step exceeding the deadline can ever be
                    # reported as a stall. Under pipelined execution
                    # run_time returns with device legs still in flight —
                    # that IS progress (backpressure, not the watchdog,
                    # bounds a slow device; every resolved leg also
                    # stamps progress via the watermark listener).
                    self.last_tick_at = _time.monotonic()
                    self._last_completed_tick = time_counter
                    if self.qos is not None:
                        self._qos_tick_feedback(
                            (_time.perf_counter() - t_tick0) * 1e3)
                    # close every live semantic result cache's
                    # invalidations/tick window (engine/result_cache.py)
                    # — the basis of the exported invalidations-per-tick
                    # rate and the bench leg's staleness accounting
                    from pathway_tpu.engine.result_cache import \
                        note_commit_ticks

                    note_commit_ticks()
                    self.monitor.update(self.scheduler, self.runner.graph,
                                        time_counter)
                    if self.persistence is not None:
                        # resolved-prefix commit watermark: checkpoint
                        # the longest prefix of ticks whose device legs
                        # have retired instead of draining the bridge —
                        # a record can still never cover a tick that
                        # could fail, but checkpoint cadence no longer
                        # prices pipelining at effective depth 1
                        self._commit_watermark_tick(time_counter)
                        if self._snapshot_due(time_counter):
                            # bounded-time recovery: operator-state
                            # snapshot anchored to the watermark + WAL
                            # compaction (engine/persistence.py)
                            self._snapshot_pass(time_counter)
                time_counter += 1
                if all_closed and not any_data:
                    # re-drain: a source may have pushed between its drain()
                    # and closing — loop until truly empty, then final tick
                    leftovers = True
                    while leftovers:
                        # unbudgeted: every source closed — deferred
                        # ingest drains to completion at full throughput
                        any_data, _closed, pushes = self._drain_and_forward(
                            time_counter, budgeted=False)
                        any_data, _closed = self._tick_sync(
                            time_counter, any_data, True, pushes)
                        leftovers = any_data
                        if leftovers:
                            self.scheduler.run_time(time_counter)
                            time_counter += 1
                    # all sources closed: end-of-stream flush tick (a hard
                    # resolve barrier under pipelined execution)
                    self.scheduler.run_time(time_counter, flush=True)
                    self._last_completed_tick = time_counter
                    if self.persistence is not None:
                        # end-of-stream keeps its hard barrier (the flush
                        # tick above) — this full commit seals and
                        # persists everything, watermark == final tick
                        self.persistence.commit(time_counter)
                        self._flush_durable_acks(time_counter)
                    break
            loop_clean = True
        except BaseException as e:  # noqa: BLE001 — escalation decides
            # poisoned device leg / exhausted persistence retries /
            # operator failure: the finally below first commits the last
            # fully-resolved prefix, then either degrade
            # (terminate_on_error=False: absorbed, recorded) or terminate
            # (re-raise to pw.run's caller after a clean teardown)
            if not self._handle_engine_failure(e):
                raise
        finally:
            # teardown: stop reader threads FIRST so nothing pushes into a
            # closed pipeline, then join them (a reader that ignores the
            # stop event is a bug the thread-leak test fixture catches)
            self._stop.set()  # natural loop exits must also stop helpers
            if self.qos is not None:
                # release the module-global hook: a later QoS-off run in
                # this process must not credit a dead run's controller
                from pathway_tpu.engine.qos import (current_controller,
                                                    install_controller)

                if current_controller() is self.qos:
                    install_controller(None)
            if self._control_client is not None:
                self._control_client.stop()
            self.watchdog.stop()
            self.supervisor.request_stop()
            for _node, session, _ds in self.sessions:
                session.stopping.set()
            self.join_readers()
            _ACTIVE_RUNTIMES.discard(self)
            if self.recorder is not None:
                # written in the finally so a crashed run still leaves its
                # trace on disk (the post-mortem artifact)
                try:
                    self.recorder.write_chrome_trace()
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "failed to write trace to %s",
                        self.recorder.trace_path, exc_info=True)
            self.monitor.close()
            self.scheduler.close()
            if self.persistence is not None:
                # final resolved-prefix commit: scheduler.close() drained
                # the bridge, so the watermark now covers every leg that
                # retired (a poisoned bridge froze it at the last clean
                # tick) — stop/crash paths keep exactly the resolved
                # prefix durable, never a tick that could still fail
                try:
                    self._commit_watermark_tick(self._last_completed_tick)
                except Exception:
                    import logging

                    logging.getLogger(__name__).warning(
                        "final watermark commit failed during teardown; "
                        "the previous commit's prefix stays durable",
                        exc_info=True)
                # final snapshot on CLEAN shutdown only, and only if the
                # watermark advanced since the last one (write_snapshot's
                # guard — no empty-generation churn). A poisoned bridge /
                # degraded run keeps operator state inconsistent with the
                # frozen watermark, so those paths stay WAL-only.
                if self._snapshots_enabled() and loop_clean \
                        and self.supervisor.fatal_error is None \
                        and self._degraded_engine_error is None \
                        and self.scheduler.take_device_error() is None:
                    try:
                        self._snapshot_pass(self._last_completed_tick)
                    except Exception:
                        import logging

                        logging.getLogger(__name__).warning(
                            "final snapshot failed during teardown; the "
                            "WAL alone stays authoritative",
                            exc_info=True)
                self.persistence.close()
            if self.replica is not None:
                self.replica.close()
            if self.profiler is not None:
                # stop the sampler + any in-flight capture; release the
                # module-global hook only if this run installed it (a
                # test-installed profiler outlives the run untouched)
                self.profiler.stop()
                if self._installed_profiler:
                    from pathway_tpu.engine.profiler import (
                        current_profiler, install_profiler)

                    if current_profiler() is self.profiler:
                        install_profiler(None)
            if self.http_server is not None:
                self.http_server.stop()
        fatal = self.supervisor.fatal_error
        if fatal is not None:
            # escalation under terminate_on_error=True: surface the
            # connector's own exception (its reader-thread traceback is
            # attached) from pw.run, after a full clean teardown
            raise fatal
        # a device leg that failed after the loop's last submit (e.g. the
        # run was stopped externally) was drained-but-not-raised by
        # scheduler.close(): surface it now, exactly as synchronous mode
        # would have raised it out of run_time — unless the degrade path
        # already absorbed and recorded this exact failure
        deferred = self.scheduler.take_device_error()
        if deferred is not None \
                and deferred is not self._degraded_engine_error:
            if self.terminate_on_error or not self._handle_engine_failure(
                    deferred):
                raise deferred
