"""Asynchronous device bridge: overlap host and device work across ticks.

The scheduler is bulk-synchronous per tick: with one thread, tokenization /
routing / pure-Python operators for tick t+1 cannot start until tick t's
encoder forward, slab scatter and top-k materialization have retired — the
TPU idles during host work and the host idles during device work (the
``framework_docs_per_s`` vs raw-kernel ``docs_per_s`` gap in bench.py).
WindVE (arxiv 2504.14941) shows a queue between the CPU stage and the
accelerator stage roughly doubles embedding throughput at equal hardware;
this module is that queue for the microbatch engine.

Model: each tick's *device leg* — the downstream closure of every
device-bound operator, stepped in topological order — is submitted as one
FIFO job ("leg") to a single worker thread. The host thread immediately
proceeds to the next tick's host-side work. Because legs are executed
strictly in tick order by one worker, every operator still observes its
ticks in order and per-tick consistency is unchanged; the overlap is purely
between tick t's device leg and tick t+1..t+K's host legs.

Guarantees:

- **Bounded in-flight window**: at most ``max_inflight`` legs (queued +
  running) exist at any moment; ``submit`` blocks (backpressure) when the
  window is full, so a slow device cannot be out-run by the host.
- **Hard barrier**: ``barrier()`` returns only when every submitted leg has
  resolved. Callers place it before anything that externalizes state —
  end-of-stream flush and reading a tick's outputs.
- **Resolved-prefix watermark**: because legs retire strictly in tick
  order, the tick of the last resolved leg is the longest *resolved
  prefix* of submitted work. ``resolved_watermark()`` exposes it as a
  monotone counter; a failed leg freezes it (the failed tick never
  enters the prefix). Persistence commits *up to the watermark* instead
  of draining the bridge (engine/streaming.py), so checkpoints trail the
  pipeline without collapsing it to depth 1.
- **Error propagation**: a leg that raises poisons the bridge; the pending
  queue is dropped (later ticks must not run on top of a failed one) and
  the *original* exception re-raises on the host thread at the next
  ``submit``/``barrier``, so user ``except`` clauses still match exactly as
  they do in synchronous mode.

The window is configured with ``PATHWAY_DEVICE_INFLIGHT`` (default 2 —
double buffering; ``1`` disables pipelining entirely).
"""

from __future__ import annotations

import os
import threading
import time as _time
import weakref
from collections import deque
from typing import Callable

from pathway_tpu.engine.profiler import current_profiler
from pathway_tpu.testing import faults

# live bridges (weak: a bridge dies with its scheduler). Out-of-band
# observers — bench.py's flight beacon, post-mortem dumps — read depth and
# the in-flight leg without a reference threaded through every layer.
_LIVE: "weakref.WeakSet[DeviceBridge]" = weakref.WeakSet()


def live_bridge_snapshot() -> dict | None:
    """Stats + in-flight leg of any live bridge (None when no bridge
    exists). With several bridges, prefers one with a leg in flight."""
    best = None
    for b in list(_LIVE):
        snap = b.stats()
        snap["inflight"] = b.inflight()
        if snap["inflight"] is not None:
            return snap
        best = best or snap
    return best


def device_inflight_from_env() -> int:
    """The configured in-flight window (>=1); 1 means synchronous."""
    raw = os.environ.get("PATHWAY_DEVICE_INFLIGHT", "2")
    try:
        return max(1, int(raw))
    except ValueError:
        return 2


class DeviceBridge:
    """FIFO dispatch queue for per-tick device legs (see module doc)."""

    def __init__(self, max_inflight: int = 2, name: str = "device-bridge",
                 recorder=None):
        self.max_inflight = max(1, int(max_inflight))
        self.name = name
        # flight recorder (engine/flight_recorder.py): leg-level spans
        # (queue-wait vs execute) and the in-flight marker for post-mortems
        self.recorder = recorder
        self._current: tuple | None = None  # (tick, started_monotonic)
        # longest resolved prefix of submitted legs: the tick of the last
        # leg that retired cleanly (FIFO worker => strictly tick-ordered
        # resolution). 0 = nothing resolved yet; frozen on leg failure.
        self._watermark = 0
        # observer fired (outside the lock, on the worker thread) after
        # every watermark advance — the streaming runtime stamps commit
        # loop progress here so a slow-but-advancing device never reads
        # as a commit stall
        self.on_advance: Callable[[int], None] | None = None
        _LIVE.add(self)
        from pathway_tpu.engine.locking import create_condition

        self._cv = create_condition("DeviceBridge._cv")
        self._queue: deque = deque()  # (tick, fn, submitted_at)
        self._running = False
        self._error: BaseException | None = None
        self._closed = False
        self._thread: threading.Thread | None = None
        self._waiters = 0  # host threads blocked in submit/barrier
        # -- instrumentation (read via stats(); exported on /metrics) ------
        self.legs_dispatched = 0
        self.legs_resolved = 0
        # legs that finished with no host thread waiting on the bridge at
        # any point of their execution: fully overlapped with host work
        self.legs_overlapped = 0
        self.queue_wait_ms = 0.0  # submit -> start, summed
        self.exec_ms = 0.0        # start -> finish, summed
        self.max_depth = 0

    # ------------------------------------------------------------------
    def depth(self) -> int:
        with self._cv:
            return len(self._queue) + (1 if self._running else 0)

    def submit(self, tick: int, fn: Callable[[], None]) -> None:
        """Enqueue one tick's device leg; blocks while the window is full.

        Raises the stored leg exception, if any — the host thread is the
        one that must observe device failures.
        """
        from pathway_tpu.engine.locking import assert_unlocked

        # submit blocks behind a full in-flight window: entering with an
        # engine lock held would stall every contender on a slow device
        assert_unlocked("DeviceBridge.submit")
        with self._cv:
            self._raise_if_error()
            if self._closed:
                raise RuntimeError("device bridge is closed")
            if self._thread is None:
                from pathway_tpu.engine.threads import spawn

                self._thread = spawn(self._work, name=self.name)
            while (len(self._queue) + (1 if self._running else 0)
                   >= self.max_inflight):
                self._waiters += 1
                try:
                    self._cv.wait()
                finally:
                    self._waiters -= 1
                self._raise_if_error()
            self._queue.append((tick, fn, _time.perf_counter()))
            self.legs_dispatched += 1
            depth = len(self._queue) + (1 if self._running else 0)
            if depth > self.max_depth:
                self.max_depth = depth
            self._cv.notify_all()

    def barrier(self) -> None:
        """Block until every submitted leg has resolved; re-raise a leg
        failure. This is the hard consistency point before commits,
        flushes and output reads."""
        from pathway_tpu.engine.locking import assert_unlocked

        assert_unlocked("DeviceBridge.barrier")
        with self._cv:
            while (self._queue or self._running) and self._error is None:
                self._waiters += 1
                try:
                    self._cv.wait()
                finally:
                    self._waiters -= 1
            self._raise_if_error()

    def close(self, join_timeout: float = 10.0) -> None:
        """Drain remaining legs and stop the worker. Leg errors are NOT
        raised here (close runs in ``finally`` paths; errors surface via
        submit/barrier) — but they stay stored for a later barrier."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(join_timeout)

    def inflight(self) -> dict | None:
        """The leg currently executing: tick + seconds since it started
        (None when idle). The operator-level detail lives on the attached
        flight recorder; this survives even with recording off, so bench's
        hang paths can always report seconds-since-dispatch."""
        cur = self._current
        if cur is None:
            return None
        return {"tick": cur[0],
                "since_s": round(_time.monotonic() - cur[1], 3)}

    def wait_watermark(self, tick: int) -> int:
        """Block until the resolved watermark reaches ``tick``; re-raise a
        leg failure. Unlike :meth:`barrier` this does NOT wait for the
        queue to drain — it waits only for the durability frontier, and
        returns the (possibly short) frontier when the bridge goes idle
        or closed without reaching ``tick`` (callers treat < tick as
        'no consistent cut available — skip'). The snapshot pass is the
        caller: at cadence ticks the host thread has just submitted leg
        ``tick`` and submits nothing more until this returns, so reaching
        the watermark means every operator sits exactly at ``tick``."""
        from pathway_tpu.engine.locking import assert_unlocked

        assert_unlocked("DeviceBridge.wait_watermark")
        with self._cv:
            while self._watermark < tick and self._error is None:
                if not self._queue and not self._running:
                    break  # idle/closed: nothing left to advance it
                self._waiters += 1
                try:
                    self._cv.wait()
                finally:
                    self._waiters -= 1
            self._raise_if_error()
            return self._watermark

    def resolved_watermark(self) -> int:
        """Tick of the longest fully-resolved prefix of submitted legs
        (monotone; 0 before anything resolved). Every leg with tick <=
        the watermark has retired cleanly — the durability frontier the
        persistence commit loop trails."""
        with self._cv:
            return self._watermark

    def error(self) -> BaseException | None:
        """The stored leg failure, if any (without raising). Lets teardown
        paths that must not raise mid-cleanup (Scheduler.close → drain)
        still surface the failure afterwards."""
        with self._cv:
            return self._error

    def stats(self) -> dict:
        with self._cv:
            resolved = self.legs_resolved
            return {
                "max_inflight": self.max_inflight,
                "depth": len(self._queue) + (1 if self._running else 0),
                "resolved_watermark": self._watermark,
                "legs_dispatched": self.legs_dispatched,
                "legs_resolved": resolved,
                "legs_overlapped": self.legs_overlapped,
                "overlap_ratio": (self.legs_overlapped / resolved
                                  if resolved else 0.0),
                "queue_wait_ms": round(self.queue_wait_ms, 3),
                "exec_ms": round(self.exec_ms, 3),
                "max_depth": self.max_depth,
            }

    # ------------------------------------------------------------------
    def _raise_if_error(self) -> None:
        if self._error is not None:
            raise self._error

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue:  # closed and drained
                    self._running = False
                    self._cv.notify_all()
                    return
                tick, fn, submitted_at = self._queue.popleft()
                self._running = True
                self._current = (tick, _time.monotonic())
                # a host thread already blocked on us? then this leg is
                # (at least partially) serialized with host work
                waited_at_start = self._waiters > 0
            rec = self.recorder
            recording = rec is not None and rec.enabled
            if recording:
                rec.mark_leg(tick)
            # profiler leg context: kernel dispatches recorded while fn()
            # runs are buffered on this thread and re-timed to the leg's
            # MEASURED execute span at end_leg — the cost model's device
            # time comes from here, not from async call-site walls
            prof = current_profiler()
            if prof is not None:
                prof.begin_leg(tick)
            started = _time.perf_counter()
            try:
                # fault points at the new watermark boundaries
                # (testing/faults.py): ``exec`` injects a device-leg
                # failure; ``resolved`` injects a crash between the leg's
                # work retiring and the watermark advancing — work done
                # but the durability frontier frozen, the edge the
                # crash-sweep suite must cover
                faults.hit("bridge.leg.exec", tick=tick)
                fn()
                faults.hit("bridge.leg.resolved", tick=tick)
            except BaseException as e:  # noqa: BLE001 — must cross threads
                if recording:
                    # poison carries the flight-recorder tail: the host
                    # thread re-raises this exact object, so the next
                    # "device leg failed" report names operator + frame
                    from pathway_tpu.engine.flight_recorder import \
                        attach_note

                    tail = rec.dump_tail()
                    if tail:
                        attach_note(
                            e, f"device leg poisoned at tick {tick}; "
                               f"flight recorder tail:\n{tail}")
                if prof is not None:
                    prof.end_leg(None)  # failed leg: no measured time
                with self._cv:
                    self._error = e
                    self._running = False
                    self._current = None
                    # later ticks must not execute on top of a failed one
                    self._queue.clear()
                    self._cv.notify_all()
                continue  # keep serving barrier wake-ups until close
            finished = _time.perf_counter()
            if prof is not None:
                prof.end_leg((finished - started) * 1e3)
            if recording:
                rec.record_leg(tick, (started - submitted_at) * 1e3,
                               (finished - started) * 1e3)
                rec.clear_leg()
            with self._cv:
                self.queue_wait_ms += (started - submitted_at) * 1e3
                self.exec_ms += (finished - started) * 1e3
                self.legs_resolved += 1
                if not waited_at_start and self._waiters == 0:
                    self.legs_overlapped += 1
                # legs resolve strictly in tick order, so this leg's tick
                # IS the longest resolved prefix
                self._watermark = tick
                self._running = False
                self._current = None
                self._cv.notify_all()
            on_advance = self.on_advance
            if on_advance is not None:
                try:
                    on_advance(tick)
                except Exception:  # observer must never poison the bridge
                    pass
