"""Stream-windowing operators: buffer / forget / freeze / forget-immediately.

Rebuild of the reference's time-column operators
(src/engine/dataflow/operators/time_column.rs:54-750 — TimeColumnBuffer/
Forget/Freeze with self-compacting timestamps) driving temporal *behaviors*
(stdlib/temporal/temporal_behavior.py). Watermark = max event-time seen in
the designated time column; thresholds are event-time values computed per
row by the behavior compiler.

This is the reference's answer to unbounded streams in bounded memory — the
"long context" of a streaming engine (SURVEY §5).
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine.delta import Delta, row_fingerprint
from pathway_tpu.engine.operators import Exchange, Operator

NEG_INF = float("-inf")


class ForgetImmediatelyOperator(Operator):
    """Pass rows through, retract them at the next processed timestamp —
    gives query streams as-of-now one-shot semantics
    (reference: forget_immediately → stdlib/temporal/_asof_now_join.py)."""

    def __init__(self):
        self.queued = Delta()

    def snapshot_state(self):
        return {"queued": self.queued.entries}

    def restore_state(self, state) -> None:
        self.queued = Delta(list(state["queued"]))

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta(self.queued.entries + delta.entries).consolidate()
        self.queued = delta.negate()
        return out


class FilterOutForgettingOperator(Operator):
    """Drop pure deletions (those not paired with a same-key insertion at the
    same time) so downstream results persist after upstream forgetting
    (reference: filter_out_results_of_forgetting)."""

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        if not delta:
            return delta
        inserted_keys = {k for k, _, d in delta.entries if d > 0}
        return Delta([
            (k, r, d) for k, r, d in delta.entries
            if d > 0 or k in inserted_keys
        ])


class _WatermarkOp(Operator):
    def __init__(self, threshold_fn: Callable, time_fn: Callable):
        self.threshold_fn = threshold_fn
        self.time_fn = time_fn
        # boxed so sharded worker replicas share one global watermark, the
        # way timely frontiers are global across workers (the scheduler
        # advances every replica's watermark before stepping any of them)
        self._wm_box: list = [NEG_INF]

    @property
    def watermark(self) -> Any:
        return self._wm_box[0]

    @watermark.setter
    def watermark(self, v: Any) -> None:
        self._wm_box[0] = v

    def exchange_specs(self):
        return [Exchange.BY_KEY]

    def replicate(self, n):
        reps = super().replicate(n)
        for r in reps[1:]:
            r._wm_box = self._wm_box
        return reps

    def _advance_watermark(self, delta: Delta) -> None:
        self._advance_watermark_value(self._watermark_candidate(delta))

    def _watermark_candidate(self, delta: Delta) -> Any:
        """Max event-time in a delta (pre-routing): the process-local
        contribution to the global watermark. Picklable scalar so it can
        ride the cluster exchange (engine/multiproc.py)."""
        best = None
        for key, row, diff in delta.entries:
            if diff > 0:
                t = self.time_fn(key, row)
                if t is not None and (best is None or _gt(t, best)):
                    best = t
        return best

    def _advance_watermark_value(self, v: Any) -> None:
        if v is not None and _gt(v, self.watermark):
            self.watermark = v

    def snapshot_state(self):
        # NEG_INF serializes as a plain -inf float; restore re-pins the
        # module sentinel so the identity checks in _gt/_le keep holding
        wm = self.watermark
        return {"wm": None if wm is NEG_INF else wm}

    def restore_state(self, state) -> None:
        wm = state["wm"]
        self.watermark = NEG_INF if wm is None else wm


def _gt(a, b):
    if b is NEG_INF:
        return True
    try:
        return a > b
    except TypeError:
        return False


def _le(a, b):
    if b is NEG_INF:
        return False
    try:
        return a <= b
    except TypeError:
        return False


class BufferOperator(_WatermarkOp):
    """Delay rows until the watermark reaches their threshold
    (behavior ``delay`` — emit once per closed window instead of per update)."""

    def __init__(self, threshold_fn, time_fn):
        super().__init__(threshold_fn, time_fn)
        self.held: dict = {}  # fingerprint -> (key, row, count)

    def snapshot_state(self):
        st = super().snapshot_state()
        st["held"] = self.held
        return st

    def restore_state(self, state) -> None:
        super().restore_state(state)
        # held is keyed (key, row_fingerprint(row)) and hash()-based
        # fingerprints vary with the process hash seed — re-key from the
        # stored rows so post-restore retractions find their entries
        self.held = {(k, row_fingerprint(r)): (k, r, c)
                     for k, r, c in state["held"].values()}

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta()
        self._advance_watermark(delta)
        for key, row, diff in delta.entries:
            thr = self.threshold_fn(key, row)
            fp = (key, row_fingerprint(row))
            if fp in self.held:
                k, r, c = self.held[fp]
                c += diff
                if c == 0:
                    del self.held[fp]
                else:
                    self.held[fp] = (k, r, c)
            elif thr is not None and _gt(thr, self.watermark):
                if diff > 0:
                    self.held[fp] = (key, row, diff)
                else:
                    out.append(key, row, diff)  # retraction of already-released row
            else:
                out.append(key, row, diff)
        # release anything whose threshold has now passed
        for fp, (key, row, c) in list(self.held.items()):
            thr = self.threshold_fn(key, row)
            if thr is None or _le(thr, self.watermark):
                out.append(key, row, c)
                del self.held[fp]
        return out.consolidate()

    def flush_all(self) -> Delta:
        out = Delta()
        for fp, (key, row, c) in self.held.items():
            out.append(key, row, c)
        self.held.clear()
        return out

    def on_time_advance(self, time):
        return Delta()

    def flush(self, time):
        return self.flush_all()


class ForgetOperator(_WatermarkOp):
    """Retract rows once the watermark passes their threshold (behavior
    ``cutoff`` — bounded state); optionally late entries are dropped."""

    def __init__(self, threshold_fn, time_fn, mark: bool = False):
        super().__init__(threshold_fn, time_fn)
        self.live: dict = {}
        self.mark = mark

    def snapshot_state(self):
        st = super().snapshot_state()
        st["live"] = self.live
        return st

    def restore_state(self, state) -> None:
        super().restore_state(state)
        # same cross-process re-keying as BufferOperator.held
        self.live = {(k, row_fingerprint(r)): (k, r, c)
                     for k, r, c in state["live"].values()}

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta()
        self._advance_watermark(delta)
        for key, row, diff in delta.entries:
            thr = self.threshold_fn(key, row)
            if thr is not None and _le(thr, self.watermark) and diff > 0:
                continue  # late row: never admitted
            fp = (key, row_fingerprint(row))
            if diff > 0:
                self.live[fp] = (key, row, self.live.get(fp, (0, 0, 0))[2] + diff)
            else:
                if fp in self.live:
                    k, r, c = self.live[fp]
                    c += diff
                    if c <= 0:
                        del self.live[fp]
                    else:
                        self.live[fp] = (k, r, c)
                else:
                    # retraction of a row we already forgot (or never
                    # admitted): dropping it keeps multiplicities >= 0
                    continue
            out.append(key, row, diff)
        # forget expired state
        for fp, (key, row, c) in list(self.live.items()):
            thr = self.threshold_fn(key, row)
            if thr is not None and _le(thr, self.watermark):
                out.append(key, row, -c)
                del self.live[fp]
        return out.consolidate()


class FreezeOperator(_WatermarkOp):
    """Stop updating rows whose threshold passed the watermark: late inserts
    and retractions for frozen thresholds are dropped."""

    def step(self, time, in_deltas):
        delta = in_deltas[0]
        out = Delta()
        self._advance_watermark(delta)
        for key, row, diff in delta.entries:
            thr = self.threshold_fn(key, row)
            if thr is not None and _le(thr, self.watermark):
                continue
            out.append(key, row, diff)
        return out
