"""Self-describing columnar wire format for the cluster exchange plane.

Replaces the ``pickle.dumps((tag, packed))`` round-trip the exchange path
paid per peer per round (the r05 regression surface — BENCH_r04→r05 took
encode+decode from 1.453 to 6.495 µs/row). The dominant payload — lists of
``(Pointer, row, diff)`` entries — serializes **column-wise** into
contiguous buffers, the shape timely's ``communication/`` crate ships
(length-prefixed byte slabs, no per-row object graph):

* the 16-byte key slab (one contiguous blob, not 20k ``Pointer`` pickles),
* one typed buffer per row column — int64 / float64 / bool / str / None
  fast paths plus nullable (``Optional``) variants — encoded with
  ``array``/``str.join`` C loops,
* an int32 diff array (widened to int64 only when a diff overflows).

Pickle is demoted to a per-column fallback for exotic value types (numpy
arrays, Json, mixed-type columns, ragged rows) and to a whole-frame
fallback (frame kind 0) if columnar encoding fails outright, so the codec
never loses data it does not understand — it just stops being fast there.

Frame layout (the transport adds its own length prefix)::

    0: 2 bytes magic  b"PW"
    2: 1 byte  version (1)
    3: 1 byte  kind    (0 = whole-frame pickle fallback, 1 = columnar)
    4: kind 0 → pickle((tag, payload))
       kind 1 → u32 tag_len | pickle(tag) | NODE(payload)

``NODE`` is a one-byte-tagged recursive encoding (dict / entry-list /
scalar fast paths / per-node pickle fallback); see the ``_N_*`` / ``_C_*``
tag tables below and README "Exchange plane" for the full spec.

Row accounting: ``encode_frame``/``decode_frame`` return the number of
*entries* they moved, counting only genuine ``(key, row, diff)`` entry
lists and **excluding** the ``wm``/``bcast`` side-channels — the
denominator of the ``pathway_tpu_exchange_*_us_per_row`` gauges measures
exchange *rows*, not watermark scalars or broadcast duplicates (the old
``_payload_rows`` counted any list it saw).
"""

from __future__ import annotations

import pickle
import struct
from array import array
from itertools import accumulate
from operator import methodcaller
from typing import Any

from pathway_tpu.internals.keys import Pointer

MAGIC = b"PW"
VERSION = 1
KIND_PICKLE = 0
KIND_COLUMNAR = 1

# node tags
_N_NONE = 0x00
_N_DICT = 0x01
_N_ENTRIES = 0x02
_N_PICKLE = 0x03
_N_INT = 0x04
_N_STR = 0x05
_N_TRUE = 0x06
_N_FALSE = 0x07
_N_FLOAT = 0x08

# column tags
_C_I64 = 0x10
_C_F64 = 0x11
_C_BOOL = 0x12
_C_STR = 0x13
_C_NONE = 0x14
_C_PKL = 0x15
_C_PTR = 0x16
_C_OPT_I64 = 0x17
_C_OPT_F64 = 0x18
_C_OPT_STR = 0x19

# row-mode byte inside an ENTRIES node
_ROWS_COLUMNAR = 0
_ROWS_PICKLE = 1

# side-channels excluded from the per-row gauge denominators: watermark
# candidates are scalars, and broadcast entries are duplicated to every
# peer — counting either would flatter encode_us_per_row
SIDE_CHANNEL_KEYS = frozenset({"wm", "bcast"})

_u32 = struct.Struct("<I")
_i64 = struct.Struct("<q")
_f64 = struct.Struct("<d")

_key_bytes = methodcaller("to_bytes", 16, "little")
_PICKLE_PROTO = pickle.HIGHEST_PROTOCOL


def _is_entry_list(obj) -> bool:
    """Same shape test the old ``_pack_payload`` used: a non-empty list
    whose first element is a 3-tuple keyed by a non-bool int."""
    if type(obj) is not list or not obj:
        return False
    e = obj[0]
    return (type(e) is tuple and len(e) == 3 and isinstance(e[0], int)
            and not isinstance(e[0], bool))


# -- column encoders ---------------------------------------------------------

def _enc_col_i64(col, out):
    out.append(bytes([_C_I64]))
    out.append(array("q", col).tobytes())


def _enc_col_f64(col, out):
    out.append(bytes([_C_F64]))
    out.append(array("d", col).tobytes())


def _enc_col_bool(col, out):
    out.append(bytes([_C_BOOL]))
    out.append(bytes(col))


def _enc_col_str(col, out):
    # char lengths (not byte offsets): the blob decodes to ONE str with a
    # single C-speed .decode(), then rows slice it by char offset
    lens = array("I", map(len, col)).tobytes()
    blob = "".join(col).encode()
    out.append(bytes([_C_STR]))
    out.append(lens)
    out.append(_u32.pack(len(blob)))
    out.append(blob)


def _enc_col_none(col, out):
    out.append(bytes([_C_NONE]))


def _enc_col_ptr(col, out):
    out.append(bytes([_C_PTR]))
    out.append(b"".join(map(_key_bytes, col)))


def _enc_col_pkl(col, out):
    blob = pickle.dumps(list(col), protocol=_PICKLE_PROTO)
    out.append(bytes([_C_PKL]))
    out.append(_u32.pack(len(blob)))
    out.append(blob)


def _mask_of(col) -> bytes:
    return bytes(v is not None for v in col)


def _enc_col_opt_i64(col, out):
    out.append(bytes([_C_OPT_I64]))
    out.append(_mask_of(col))
    out.append(array("q", [v for v in col if v is not None]).tobytes())


def _enc_col_opt_f64(col, out):
    out.append(bytes([_C_OPT_F64]))
    out.append(_mask_of(col))
    out.append(array("d", [v for v in col if v is not None]).tobytes())


def _enc_col_opt_str(col, out):
    present = [v for v in col if v is not None]
    blob = "".join(present).encode()
    out.append(bytes([_C_OPT_STR]))
    out.append(_mask_of(col))
    out.append(array("I", map(len, present)).tobytes())
    out.append(_u32.pack(len(blob)))
    out.append(blob)


_NONE_T = type(None)
_COL_ENCODERS = {
    frozenset((int,)): _enc_col_i64,
    frozenset((float,)): _enc_col_f64,
    frozenset((bool,)): _enc_col_bool,
    frozenset((str,)): _enc_col_str,
    frozenset((_NONE_T,)): _enc_col_none,
    frozenset((Pointer,)): _enc_col_ptr,
    frozenset((int, _NONE_T)): _enc_col_opt_i64,
    frozenset((float, _NONE_T)): _enc_col_opt_f64,
    frozenset((str, _NONE_T)): _enc_col_opt_str,
}


def _enc_column(col, out) -> None:
    enc = _COL_ENCODERS.get(frozenset(map(type, col)), _enc_col_pkl)
    if enc is _enc_col_pkl:
        enc(col, out)
        return
    mark = len(out)
    try:
        enc(col, out)
    except (OverflowError, ValueError, UnicodeEncodeError):
        # ints past int64, pathological lengths, lone surrogates: the
        # typed path refuses, pickle carries the column instead
        del out[mark:]
        _enc_col_pkl(col, out)


def _enc_entries(ents: list, out: list) -> bool:
    """Columnar entry-list encoding. Returns False (with ``out``
    untouched) when the list does not actually have uniform
    ``(key, row, diff)`` shape — caller falls back to pickle."""
    mark = len(out)
    n = len(ents)
    try:
        # every element must be a genuine 3-tuple — _is_entry_list only
        # probed the first one, and encoding e[0..2] of a longer tuple
        # would silently drop its tail (lossy, violates the module
        # contract); non-tuples raise TypeError into the fallback
        if set(map(len, ents)) != {3} \
                or set(map(type, ents)) != {tuple}:
            return False
        keys = b"".join(_key_bytes(e[0]) for e in ents)
        diffs = [e[2] for e in ents]
    except (TypeError, ValueError, OverflowError, IndexError):
        return False
    try:
        dfmt, dblob = b"i", array("i", diffs).tobytes()
    except (OverflowError, TypeError):
        try:
            dfmt, dblob = b"q", array("q", diffs).tobytes()
        except (OverflowError, TypeError):
            del out[mark:]
            return False
    rows = [e[1] for e in ents]
    out.append(bytes([_N_ENTRIES]))
    out.append(_u32.pack(n))
    out.append(dfmt)
    out.append(dblob)
    out.append(keys)
    if set(map(type, rows)) == {tuple} and len(set(map(len, rows))) == 1:
        cols = list(zip(*rows))
        out.append(bytes([_ROWS_COLUMNAR]))
        out.append(_u32.pack(len(cols)))
        for col in cols:
            _enc_column(col, out)
    else:
        # ragged or non-tuple rows: keys/diffs still ship columnar, rows
        # ride one pickle blob
        blob = pickle.dumps(rows, protocol=_PICKLE_PROTO)
        out.append(bytes([_ROWS_PICKLE]))
        out.append(_u32.pack(len(blob)))
        out.append(blob)
    return True


def _enc_pickle_node(obj, out) -> None:
    blob = pickle.dumps(obj, protocol=_PICKLE_PROTO)
    out.append(bytes([_N_PICKLE]))
    out.append(_u32.pack(len(blob)))
    out.append(blob)


def _enc_node(obj, out: list, ctr: list, count: bool) -> None:
    if obj is None:
        out.append(bytes([_N_NONE]))
        return
    t = type(obj)
    if t is dict:
        out.append(bytes([_N_DICT]))
        out.append(_u32.pack(len(obj)))
        for k, v in obj.items():
            _enc_node(k, out, ctr, count)
            _enc_node(v, out, ctr,
                      count and k not in SIDE_CHANNEL_KEYS)
        return
    if _is_entry_list(obj):
        if _enc_entries(obj, out):
            if count:
                ctr[0] += len(obj)
            return
        _enc_pickle_node(obj, out)
        return
    if t is bool:
        out.append(bytes([_N_TRUE if obj else _N_FALSE]))
        return
    if t is int:
        try:
            out.append(bytes([_N_INT]) + _i64.pack(obj))
        except struct.error:
            _enc_pickle_node(obj, out)
        return
    if t is float:
        out.append(bytes([_N_FLOAT]) + _f64.pack(obj))
        return
    if t is str:
        b = obj.encode()
        out.append(bytes([_N_STR]))
        out.append(_u32.pack(len(b)))
        out.append(b)
        return
    _enc_pickle_node(obj, out)


def encode_frame(tag: Any, payload: Any) -> tuple[list[bytes], int, int]:
    """Encode ``(tag, payload)`` into wire chunks.

    Returns ``(chunks, total_bytes, n_rows)``; the transport either joins
    the chunks behind a length prefix (TCP) or writes them sequentially
    into a shared-memory slot (no join, no intermediate copy). Any
    columnar-encode failure falls back to a whole-frame pickle (kind 0) —
    the wire never refuses a payload pickle could carry.
    """
    ctr = [0]
    out: list[bytes] = [MAGIC + bytes([VERSION, KIND_COLUMNAR])]
    try:
        tag_blob = pickle.dumps(tag, protocol=_PICKLE_PROTO)
        out.append(_u32.pack(len(tag_blob)))
        out.append(tag_blob)
        _enc_node(payload, out, ctr, True)
    except Exception:
        blob = pickle.dumps((tag, payload), protocol=_PICKLE_PROTO)
        out = [MAGIC + bytes([VERSION, KIND_PICKLE]), blob]
        ctr[0] = payload_rows(payload)
    return out, sum(map(len, out)), ctr[0]


# -- decoding ----------------------------------------------------------------

class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf):
        self.buf = buf
        self.pos = 0

    def take(self, n: int):
        p = self.pos
        self.pos = p + n
        return self.buf[p:p + n]

    def u8(self) -> int:
        p = self.pos
        self.pos = p + 1
        return self.buf[p]

    def u32(self) -> int:
        return _u32.unpack(self.take(4))[0]


def _dec_keys(cur: _Cursor, n: int) -> list:
    kv = cur.take(16 * n)
    ifb = int.from_bytes
    P = Pointer
    return [P(ifb(kv[i:i + 16], "little")) for i in range(0, 16 * n, 16)]


def _dec_str_block(cur: _Cursor, m: int) -> list:
    lens = array("I")
    lens.frombytes(bytes(cur.take(4 * m)))
    blob_len = cur.u32()
    s = bytes(cur.take(blob_len)).decode()
    offs = [0, *accumulate(lens)]
    return [s[offs[i]:offs[i + 1]] for i in range(m)]


def _fill_optional(mask, present: list) -> list:
    it = iter(present)
    return [next(it) if flag else None for flag in mask]


def _dec_column(cur: _Cursor, n: int) -> list:
    ct = cur.u8()
    if ct == _C_I64:
        a = array("q")
        a.frombytes(bytes(cur.take(8 * n)))
        return a.tolist()
    if ct == _C_F64:
        a = array("d")
        a.frombytes(bytes(cur.take(8 * n)))
        return a.tolist()
    if ct == _C_BOOL:
        return list(map(bool, cur.take(n)))
    if ct == _C_STR:
        return _dec_str_block(cur, n)
    if ct == _C_NONE:
        return [None] * n
    if ct == _C_PTR:
        return _dec_keys(cur, n)
    if ct == _C_PKL:
        blob_len = cur.u32()
        # pwt-ok: PWT306 — intra-fleet exchange frames from peers the
        # same supervisor spawned (HMAC-authenticated transport), not a
        # snapshot restore path; cell payloads carry arbitrary UDF types
        # a name whitelist cannot enumerate
        return pickle.loads(bytes(cur.take(blob_len)))
    if ct == _C_OPT_I64:
        mask = bytes(cur.take(n))
        a = array("q")
        a.frombytes(bytes(cur.take(8 * sum(mask))))
        return _fill_optional(mask, a.tolist())
    if ct == _C_OPT_F64:
        mask = bytes(cur.take(n))
        a = array("d")
        a.frombytes(bytes(cur.take(8 * sum(mask))))
        return _fill_optional(mask, a.tolist())
    if ct == _C_OPT_STR:
        mask = bytes(cur.take(n))
        return _fill_optional(mask, _dec_str_block(cur, sum(mask)))
    raise ValueError(f"unknown wire column tag 0x{ct:02x}")


def _dec_entries(cur: _Cursor, ctr: list, count: bool) -> list:
    n = cur.u32()
    dfmt = chr(cur.u8())
    diffs = array(dfmt)
    diffs.frombytes(bytes(cur.take(n * diffs.itemsize)))
    keys = _dec_keys(cur, n)
    rowmode = cur.u8()
    if rowmode == _ROWS_COLUMNAR:
        ncols = cur.u32()
        cols = [_dec_column(cur, n) for _ in range(ncols)]
        rows = list(zip(*cols)) if cols else [()] * n
    else:
        blob_len = cur.u32()
        # pwt-ok: PWT306 — trusted intra-fleet wire protocol (see
        # _dec_column); not a restore path
        rows = pickle.loads(bytes(cur.take(blob_len)))
    if count:
        ctr[0] += n
    return list(zip(keys, rows, diffs.tolist()))


def _dec_node(cur: _Cursor, ctr: list, count: bool):
    nt = cur.u8()
    if nt == _N_NONE:
        return None
    if nt == _N_DICT:
        n = cur.u32()
        out = {}
        for _ in range(n):
            k = _dec_node(cur, ctr, count)
            out[k] = _dec_node(cur, ctr,
                               count and k not in SIDE_CHANNEL_KEYS)
        return out
    if nt == _N_ENTRIES:
        return _dec_entries(cur, ctr, count)
    if nt == _N_PICKLE:
        blob_len = cur.u32()
        # pwt-ok: PWT306 — trusted intra-fleet wire protocol (see
        # _dec_column); not a restore path
        return pickle.loads(bytes(cur.take(blob_len)))
    if nt == _N_INT:
        return _i64.unpack(cur.take(8))[0]
    if nt == _N_STR:
        n = cur.u32()
        return bytes(cur.take(n)).decode()
    if nt == _N_TRUE:
        return True
    if nt == _N_FALSE:
        return False
    if nt == _N_FLOAT:
        return _f64.unpack(cur.take(8))[0]
    raise ValueError(f"unknown wire node tag 0x{nt:02x}")


def decode_frame(buf) -> tuple[Any, Any, int]:
    """Decode one wire frame (bytes or memoryview — shared-memory slots
    decode in place, no intermediate copy). Returns
    ``(tag, payload, n_rows)``."""
    view = memoryview(buf)
    if bytes(view[:2]) != MAGIC:
        raise ValueError("bad exchange frame magic (protocol skew?)")
    version, kind = view[2], view[3]
    if version != VERSION:
        raise ValueError(f"unsupported exchange wire version {version}")
    if kind == KIND_PICKLE:
        # pwt-ok: PWT306 — trusted intra-fleet wire protocol (see
        # _dec_column); not a restore path
        tag, payload = pickle.loads(view[4:])
        return tag, payload, payload_rows(payload)
    cur = _Cursor(view)
    cur.pos = 4
    tag_len = cur.u32()
    # pwt-ok: PWT306 — trusted intra-fleet wire protocol (see
    # _dec_column); not a restore path
    tag = pickle.loads(bytes(cur.take(tag_len)))
    ctr = [0]
    payload = _dec_node(cur, ctr, True)
    return tag, payload, ctr[0]


def payload_rows(obj, count: bool = True) -> int:
    """Entry count of a raw (unencoded) exchange payload — genuine entry
    lists only; ``wm``/``bcast`` side-channels, scalars, and plain lists
    count zero (the per-row gauges divide by *rows moved*, nothing else).
    """
    if _is_entry_list(obj):
        return len(obj) if count else 0
    if isinstance(obj, dict):
        return sum(
            payload_rows(v, count and k not in SIDE_CHANNEL_KEYS)
            for k, v in obj.items())
    return 0
