"""Request-scoped serving-path tracing + SLO accounting.

The flight recorder (engine/flight_recorder.py) answers "what is each
*operator* doing"; this module answers "where did each *query* spend its
time". A request id is assigned at webserver ingress (io/http/), and the
span is stamped at five fixed hand-off points as the request crosses the
serving path:

    ingress         arrival at the webserver dispatch (t_ingress)
    admission       entering the QoS admission gate (t_admission)
    enqueued        row pushed into the connector session (t_enqueued)
    tick start      the commit loop drained the row (t_tick_start)
    host-leg done   the scheduler finished the tick's host leg (t_host_done)
    resolved        response_writer resolved the request key (t_resolved)
    responded       the HTTP handler returned the value (t_responded)

Consecutive stamps define the six stages reported everywhere
(:data:`STAGES`): ``ingress_wait`` (parse/validate), ``admission_wait``
(time queued at the QoS admission gate, engine/qos.py — ~0 with QoS
off), ``queue`` (waiting for the commit tick), ``host`` (host-leg
compute), ``device`` (device-leg dispatch through resolution — in
synchronous mode the host leg subsumes it), ``response_write`` (event
wake + serialization). Stamps are normalized to a monotone sequence (a
missing or out-of-order stamp snaps to its predecessor), so the stage
decomposition **telescopes**: the six stages sum to the wall-clock e2e
total by construction, which is the contract
tests/test_request_tracing.py pins.

Aggregation is streaming and bounded: P² quantile estimators
(Jain & Chlamtac 1985) for e2e p50/p95/p99 and per-stage p50, a sliding
window for the SLO burn rate (observed violation ratio over the allowed
error budget), and a ring of the last N over-budget requests with their
dominant stage (``/status.slow_queries``). Completed spans also keep
their raw stamps in a bounded ring so the flight recorder can join them
onto the Perfetto trace as a third track.

The tracker is created iff the flight recorder is enabled; request ids
never enter engine rows, so pipeline outputs are byte-identical with
tracing on or off.
"""

from __future__ import annotations

import collections
import time
import weakref

# live trackers (weak: a tracker dies with its recorder/run). Lets the
# knn index attribute its tenant id to in-flight spans by engine key
# without a reference threaded through the operator graph.
_LIVE: "weakref.WeakSet[RequestTracker]" = weakref.WeakSet()


def live_trackers() -> list["RequestTracker"]:
    """Every live request tracker (the tenant-attribution hook in
    ops/knn.py iterates this; usually zero or one)."""
    return list(_LIVE)

# stage names, in hand-off order (see module doc)
STAGES = ("ingress_wait", "admission_wait", "queue", "host", "device",
          "response_write")

# router-side stages a request crosses BEFORE the five above begin on the
# serving process (the fleet prefix of the decomposition): `route` is the
# endpoint choice, `forward` the first proxy attempt, `failover` every
# replay on the next-best replica. Recorded per query by the router's
# RouterRequestLog (engine/fleet_observability.py) under the SAME request
# id the serving process adopts, so the merged fleet trace shows one
# query's router + process stages end to end.
ROUTER_STAGES = ("route", "forward", "failover")

_DEFAULT_SLO_E2E_MS = 20.0       # BASELINE.md serving target
_DEFAULT_ERROR_BUDGET = 0.01     # 1% of requests may exceed the SLO
_DEFAULT_WINDOW = 256            # burn-rate sliding window (requests)
_DEFAULT_SLOW_TAIL = 16          # /status.slow_queries depth
_DEFAULT_TRACE_SPANS = 512       # completed spans kept for the trace


class P2Quantile:
    """Streaming quantile estimator (the P² algorithm): O(1) memory,
    O(1) per observation, no sample retention. Exact until 5
    observations, then parabolic marker interpolation."""

    __slots__ = ("q", "count", "_init", "_heights", "_pos", "_desired",
                 "_inc")

    def __init__(self, q: float):
        assert 0.0 < q < 1.0
        self.q = q
        self.count = 0
        self._init: list[float] = []
        self._heights: list[float] = []
        self._pos: list[int] = []
        self._desired: list[float] = []
        self._inc = (0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0)

    def observe(self, x: float) -> None:
        self.count += 1
        if self._heights == []:
            self._init.append(float(x))
            if len(self._init) == 5:
                self._init.sort()
                self._heights = list(self._init)
                self._pos = [1, 2, 3, 4, 5]
                q = self.q
                self._desired = [1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q,
                                 3.0 + 2.0 * q, 5.0]
            return
        h, n, d = self._heights, self._pos, self._desired
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and not (h[k] <= x < h[k + 1]):
                k += 1
        for i in range(k + 1, 5):
            n[i] += 1
        for i in range(5):
            d[i] += self._inc[i]
        for i in (1, 2, 3):
            diff = d[i] - n[i]
            if (diff >= 1.0 and n[i + 1] - n[i] > 1) or \
                    (diff <= -1.0 and n[i - 1] - n[i] < -1):
                s = 1 if diff >= 1.0 else -1
                # parabolic (P²) candidate, falling back to linear when it
                # would break marker-height monotonicity
                cand = h[i] + s / (n[i + 1] - n[i - 1]) * (
                    (n[i] - n[i - 1] + s) * (h[i + 1] - h[i])
                    / (n[i + 1] - n[i])
                    + (n[i + 1] - n[i] - s) * (h[i] - h[i - 1])
                    / (n[i] - n[i - 1]))
                if not (h[i - 1] < cand < h[i + 1]):
                    cand = h[i] + s * (h[i + s] - h[i]) / (n[i + s] - n[i])
                h[i] = cand
                n[i] += s

    def value(self) -> float | None:
        """Current estimate (exact below 5 observations; None when
        nothing was observed)."""
        if self._heights:
            return self._heights[2]
        if not self._init:
            return None
        xs = sorted(self._init)
        # nearest-rank on the tiny exact prefix
        idx = min(len(xs) - 1, max(0, round(self.q * (len(xs) - 1))))
        return xs[idx]


class RequestSpan:
    """One in-flight (or completed) request's stamp set. Mutated by the
    webserver thread (ingress/enqueued/responded), the commit loop
    (tick start / host done) and the device-bridge worker (resolved);
    every stamp is a single attribute store, ordered by the pipeline's
    own hand-off sequence."""

    __slots__ = ("rid", "route", "key", "tick", "tenant", "t_ingress",
                 "t_admission", "t_enqueued", "t_tick_start", "t_host_done",
                 "t_resolved", "t_responded")

    def __init__(self, rid: str, route: str, t_ingress: float):
        self.rid = rid
        self.route = route
        self.key = None
        self.tick: int | None = None
        self.tenant: str | None = None
        self.t_ingress = t_ingress
        self.t_admission: float | None = None
        self.t_enqueued: float | None = None
        self.t_tick_start: float | None = None
        self.t_host_done: float | None = None
        self.t_resolved: float | None = None
        self.t_responded: float | None = None

    def normalized_stamps(self) -> list[float]:
        """The seven stamps as a monotone sequence: a missing or
        out-of-order stamp snaps to its predecessor, so consecutive
        differences are non-negative and telescope exactly to
        ``t_responded - t_ingress``."""
        raw = (self.t_ingress, self.t_admission, self.t_enqueued,
               self.t_tick_start, self.t_host_done, self.t_resolved,
               self.t_responded)
        out = [raw[0]]
        cur = raw[0]
        for t in raw[1:]:
            if t is None or t < cur:
                t = cur
            out.append(t)
            cur = t
        return out

    def stages_ms(self) -> dict[str, float]:
        norm = self.normalized_stamps()
        return {name: (norm[i + 1] - norm[i]) * 1e3
                for i, name in enumerate(STAGES)}


class RequestTracker:
    """Thread-safe per-request span store + streaming SLO aggregates
    (see module doc). One per run, owned by the flight recorder."""

    def __init__(self, slo_ms: float | None = None,
                 error_budget: float | None = None):
        from pathway_tpu.internals.config import _env_float, _env_int

        self.slo_ms = slo_ms if slo_ms is not None else _env_float(
            "PATHWAY_SLO_E2E_MS", _DEFAULT_SLO_E2E_MS)
        budget = error_budget if error_budget is not None else _env_float(
            "PATHWAY_SLO_ERROR_BUDGET", _DEFAULT_ERROR_BUDGET)
        self.error_budget = max(1e-6, budget)
        from pathway_tpu.engine.locking import create_lock

        self._lock = create_lock("RequestTracker._lock")
        self._by_key: dict = {}
        self._by_tick: dict[int, list[RequestSpan]] = {}
        self.completed: collections.deque = collections.deque(
            maxlen=max(8, _env_int("PATHWAY_REQUEST_TRACE_SPANS",
                                   _DEFAULT_TRACE_SPANS)))
        self.slow: collections.deque = collections.deque(
            maxlen=max(1, _env_int("PATHWAY_SLOW_QUERY_TAIL",
                                   _DEFAULT_SLOW_TAIL)))
        self._window: collections.deque = collections.deque(
            maxlen=max(16, _env_int("PATHWAY_SLO_WINDOW", _DEFAULT_WINDOW)))
        self.count = 0
        self.sum_ms = 0.0
        self.violations = 0
        self._e2e_q = {0.5: P2Quantile(0.5), 0.95: P2Quantile(0.95),
                       0.99: P2Quantile(0.99)}
        self._stage_p50 = {s: P2Quantile(0.5) for s in STAGES}
        self._stage_sum = {s: 0.0 for s in STAGES}
        # per-tenant aggregates, populated only for spans a tenant-owning
        # index attributed (attribute_tenant): tenant -> state dict
        self._tenants: dict[str, dict] = {}
        self._tenant_window = max(
            16, _env_int("PATHWAY_SLO_WINDOW", _DEFAULT_WINDOW))
        _LIVE.add(self)

    # -- write side (stamping, in hand-off order) --------------------------
    def start(self, rid: str, route: str, t_ingress: float) -> RequestSpan:
        return RequestSpan(rid, route, t_ingress)

    def admission(self, span: RequestSpan) -> None:
        """The handler is about to enter the QoS admission gate
        (engine/qos.py): everything before this stamp is parse/validate
        (``ingress_wait``); the gap to the enqueue stamp is
        ``admission_wait`` — time the query spent queued (or deliberated
        over) at admission. With QoS off the gate is a no-op and this is
        stamped immediately before the enqueue, so the stage reads ~0."""
        span.t_admission = time.perf_counter()

    def enqueued(self, span: RequestSpan, key) -> None:
        """Row built and about to be pushed; registers the engine key so
        drain/resolve can find the span. MUST run before session.push —
        the commit loop may drain the row immediately."""
        span.t_enqueued = time.perf_counter()
        span.key = key
        with self._lock:
            self._by_key[key] = span

    def picked_up(self, entries, tick: int) -> None:
        """The commit loop drained ``entries`` for the tick about to
        run. Called only for sessions of request-tracking sources, and
        only when requests are in flight."""
        if not self._by_key:
            return
        t = time.perf_counter()
        with self._lock:
            for key, _row, diff in entries:
                if diff <= 0:
                    continue  # delete_completed_queries retraction
                span = self._by_key.get(key)
                if span is not None and span.t_tick_start is None:
                    span.t_tick_start = t
                    span.tick = tick
                    self._by_tick.setdefault(tick, []).append(span)

    def active(self) -> bool:
        """Any request picked up and awaiting its host-leg stamp? One
        truthiness probe — the scheduler calls this every tick."""
        return bool(self._by_tick)

    def host_done(self, tick: int) -> None:
        """The scheduler finished ``tick``'s host leg (about to submit /
        step the device leg)."""
        if tick not in self._by_tick:
            return
        t = time.perf_counter()
        # under the lock: finish() on the event-loop thread removes spans
        # from this same list (a request resolved mid-tick), and an
        # unlocked iteration could skip a sibling span entirely
        with self._lock:
            for span in self._by_tick.get(tick, ()):
                if span.t_host_done is None:
                    span.t_host_done = t

    def attribute_tenant(self, keys, tenant: str) -> None:
        """Attach ``tenant`` to the in-flight spans registered under
        ``keys``. Called by the index that owns the tenant id
        (ops/knn.py search — the query keys there ARE the engine keys
        registered at enqueue); unknown keys are other sources' rows and
        are skipped. First attribution wins: the tenant of the index a
        query actually searched."""
        with self._lock:
            for key in keys:
                span = self._by_key.get(key)
                if span is not None and span.tenant is None:
                    span.tenant = tenant

    def resolved(self, key) -> None:
        """response_writer resolved ``key`` (host thread in synchronous
        mode, bridge worker under pipelining)."""
        span = self._by_key.get(key)
        if span is not None and span.t_resolved is None:
            span.t_resolved = time.perf_counter()

    def finish(self, span: RequestSpan) -> None:
        """Handler is returning (or unwinding). A resolved span completes
        and feeds the aggregates; an unresolved one (client disconnect,
        handler error) is abandoned without polluting the SLO numbers."""
        if span.t_resolved is None:
            self._discard(span)
            return
        span.t_responded = time.perf_counter()
        stages = span.stages_ms()
        e2e = (span.normalized_stamps()[-1] - span.t_ingress) * 1e3
        dominant = max(stages, key=stages.get)
        record = {
            "request_id": span.rid,
            "route": span.route,
            "tick": span.tick,
            "e2e_ms": round(e2e, 3),
            "stages": {k: round(v, 3) for k, v in stages.items()},
            "dominant_stage": dominant,
            "t0": span.t_ingress,
            "stamps": span.normalized_stamps(),
            "over_budget": e2e > self.slo_ms,
            "at": time.time(),
        }
        if span.tenant is not None:
            record["tenant"] = span.tenant
        with self._lock:
            self._discard_locked(span)
            self.count += 1
            self.sum_ms += e2e
            self._window.append(e2e)
            for q in self._e2e_q.values():
                q.observe(e2e)
            for s, ms in stages.items():
                self._stage_sum[s] += ms
                self._stage_p50[s].observe(ms)
            if span.tenant is not None:
                ts = self._tenants.get(span.tenant)
                if ts is None:
                    ts = self._tenants[span.tenant] = {
                        "count": 0,
                        "p50": P2Quantile(0.5),
                        "p95": P2Quantile(0.95),
                        "window": collections.deque(
                            maxlen=self._tenant_window),
                    }
                ts["count"] += 1
                ts["p50"].observe(e2e)
                ts["p95"].observe(e2e)
                ts["window"].append(e2e)
            self.completed.append(record)
            if record["over_budget"]:
                self.violations += 1
                self.slow.append(record)

    def _discard(self, span: RequestSpan) -> None:
        with self._lock:
            self._discard_locked(span)

    def _discard_locked(self, span: RequestSpan) -> None:
        if span.key is not None:
            cur = self._by_key.get(span.key)
            if cur is span:
                del self._by_key[span.key]
        if span.tick is not None:
            spans = self._by_tick.get(span.tick)
            if spans is not None:
                try:
                    spans.remove(span)
                except ValueError:
                    pass
                if not spans:
                    del self._by_tick[span.tick]

    # -- read side ---------------------------------------------------------
    def quantiles_ms(self) -> dict[float, float] | None:
        """{0.5: p50, 0.95: p95, 0.99: p99} in ms, None before the first
        completed request. Values are sorted so the exposed set is always
        monotone (independent P² estimators can cross transiently)."""
        with self._lock:
            vals = [q.value() for q in self._e2e_q.values()]
        if any(v is None for v in vals):
            return None
        vals.sort()
        return dict(zip(sorted(self._e2e_q), vals))

    def window_size(self) -> int:
        """Completed requests currently in the burn-rate window. The QoS
        admission gate (engine/qos.py) refuses to make burn-based shed
        decisions on a near-empty window: one compile-time outlier must
        not read as '100x the error budget' and wedge the gate shut."""
        with self._lock:
            return len(self._window)

    def window_p50_ms(self) -> float | None:
        """Median e2e over the RECENT window (not the run-wide P²
        estimator, which warmup-compile outliers drag for hundreds of
        observations). The QoS gate's latency prediction uses this: an
        admission decision is about the system as it is NOW."""
        with self._lock:
            if not self._window:
                return None
            xs = sorted(self._window)
            return xs[len(xs) // 2]

    def burn_rate(self) -> float:
        """Observed violation ratio over the sliding window, divided by
        the allowed error budget: 1.0 = burning exactly the budget,
        >1.0 = on track to exhaust it."""
        with self._lock:
            if not self._window:
                return 0.0
            viol = sum(1 for v in self._window if v > self.slo_ms)
            return (viol / len(self._window)) / self.error_budget

    def tenant_summary(self) -> dict[str, dict]:
        """Per-tenant serving aggregates: {tenant: {count, p50_ms,
        p95_ms, burn_rate}}. Burn rate uses the tenant's OWN sliding
        window against the shared SLO + error budget — one noisy tenant
        reads >1.0 while its neighbours stay at 0 (the multi-tenant
        isolation signal /metrics exports)."""
        out: dict[str, dict] = {}
        with self._lock:
            for tenant, ts in self._tenants.items():
                win = ts["window"]
                viol = sum(1 for v in win if v > self.slo_ms)
                burn = ((viol / len(win)) / self.error_budget
                        if win else 0.0)
                p50 = ts["p50"].value()
                p95 = ts["p95"].value()
                # independent P2 estimators can cross transiently; keep
                # the exported pair monotone like quantiles_ms does
                if p50 is not None and p95 is not None and p95 < p50:
                    p50, p95 = p95, p50
                out[tenant] = {
                    "count": ts["count"],
                    "p50_ms": None if p50 is None else round(p50, 3),
                    "p95_ms": None if p95 is None else round(p95, 3),
                    "burn_rate": round(burn, 3),
                }
        return out

    def stage_summary(self) -> dict[str, dict]:
        with self._lock:
            return {
                s: {"p50_ms": self._stage_p50[s].value(),
                    "sum_ms": round(self._stage_sum[s], 3)}
                for s in STAGES
            }

    def slow_queries(self) -> list[dict]:
        """Last-N over-budget requests, oldest first, each naming its
        dominant stage (the /status.slow_queries contract)."""
        with self._lock:
            return [dict(r, stages=dict(r["stages"])) for r in self.slow]

    def trace_spans(self) -> list[dict]:
        """Completed spans (bounded ring) with raw perf_counter stamps,
        for the flight recorder's Perfetto request track."""
        with self._lock:
            return list(self.completed)

    def summary(self) -> dict:
        """Compact serving snapshot for /status and the dashboard."""
        qs = self.quantiles_ms()
        with self._lock:
            inflight = len(self._by_key)
        out = {
            "requests": self.count,
            "inflight": inflight,
            "slo_ms": self.slo_ms,
            "error_budget": self.error_budget,
            "violations": self.violations,
            "burn_rate": round(self.burn_rate(), 3),
        }
        if qs is not None:
            out["e2e_ms"] = {"p50": round(qs[0.5], 3),
                             "p95": round(qs[0.95], 3),
                             "p99": round(qs[0.99], 3)}
            out["stages"] = {
                s: (None if v["p50_ms"] is None else round(v["p50_ms"], 3))
                for s, v in self.stage_summary().items()
            }
        tenants = self.tenant_summary()
        if tenants:
            out["tenants"] = tenants
        return out
