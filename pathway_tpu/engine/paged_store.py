"""Paged HBM vector store: device page pool + host-side page table.

The KNN slab (ops/knn.py) historically was ONE contiguous device array:
growth doubled capacity with a stop-the-world host realloc + full device
re-upload, and the fused donated-slab ingest could not grow at all (the
donated shape is pinned). This module adopts the paged-memory design from
Ragged Paged Attention (PAPERS.md): HBM is carved into fixed-size pages
(``PATHWAY_PAGE_ROWS`` vector rows each, plus per-row validity and — for
int8 slabs — quantization scale/norm side columns), a host-side page table
maps logical slots to (page, offset), and device memory is allocated in
page-aligned **extents** that are never moved or copied once created:

- growth appends a new extent (fresh device allocation, established as
  zeros ON DEVICE) — existing extents, and the donated buffers the fused
  ingest scatters into, are untouched (EdgeRAG-style online indexing: no
  re-quantization copies);
- frees return pages to a free list, so delete/ingest churn reuses pages
  instead of growing the pool (occupancy stays bounded);
- pages carry a tenant tag with optional per-tenant page quotas — the
  allocation unit for many small indexes sharing one device.

The pool owns page accounting and the per-extent device/host bookkeeping
containers; the search/scatter kernels stay in ops/knn.py and
parallel/sharded_knn.py (they operate per extent). Callers hold the owning
index's lock around every pool call — the pool itself is not synchronized.
"""

from __future__ import annotations

import os
import weakref
from typing import Any, Hashable

import numpy as np

_DEFAULT_PAGE_ROWS = 1024


class PageQuotaExceeded(RuntimeError):
    """A tenant asked for pages beyond its configured quota. Growth cannot
    help (the quota, not the pool, is the limit), so this escapes instead
    of looping the grow path."""


def paged_store_enabled(override: bool | None = None) -> bool:
    """Paged device storage is the default; ``PATHWAY_PAGED_STORE=0``
    selects the legacy contiguous-slab path (kept for rollback and as the
    byte-identical reference the paged tests pin against)."""
    if override is not None:
        return bool(override)
    return os.environ.get("PATHWAY_PAGED_STORE", "1").lower() not in (
        "0", "false", "off", "no")


def page_rows(override: int | None = None) -> int:
    """Rows per page. Must be a power of two in [128, 2^19] so pages tile
    both the 128-lane layout and the chunked-scan kernel's chunk size
    (ops/knn.py ``_CHUNK_ROWS``)."""
    rows = override if override is not None else int(
        os.environ.get("PATHWAY_PAGE_ROWS", _DEFAULT_PAGE_ROWS))
    if rows < 128 or rows > (1 << 19) or rows & (rows - 1):
        raise ValueError(
            f"page_rows must be a power of two in [128, {1 << 19}]; got "
            f"{rows} (PATHWAY_PAGE_ROWS)")
    return rows


def quota_pages(quota_rows: int, rows_per_page: int) -> int:
    """Pages a row quota buys — rounded UP, so a non-page-aligned quota
    silently over-grants (the static checker flags this as PWT111)."""
    return -(-int(quota_rows) // rows_per_page)


class _Page:
    __slots__ = ("pid", "base", "region", "free", "live", "tenant")

    def __init__(self, pid: int, base: int, region: Hashable,
                 rows: int):
        self.pid = pid
        self.base = base          # global row id of offset 0
        self.region = region      # (extent index) or (extent, shard)
        self.free = list(range(rows - 1, -1, -1))  # LIFO offsets
        self.live = 0
        self.tenant: Hashable | None = None


class PageAllocator:
    """Host-side page table: slot allocation within fixed-size pages.

    Pages belong to a *region* (the device extent — or (extent, shard)
    block for the mesh-sharded store) fixed at registration, and are
    claimed by a *tenant* on first allocation. A page with live rows is
    "open" for its tenant; a page whose last row is freed returns to its
    region's free list (tenant tag cleared) — the reuse that keeps
    occupancy bounded under ingest/delete churn.

    Global row ids are contiguous across regions and every region base is
    page-aligned, so ``slot // page_rows`` IS the page id — the page table
    needs no search structure.
    """

    def __init__(self, rows_per_page: int,
                 tenant_quotas: dict[Hashable, int] | None = None):
        self.page_rows = int(rows_per_page)
        self.pages: list[_Page] = []
        # region → LIFO of unclaimed page ids; insertion order preserved
        self._free_pages: dict[Hashable, list[int]] = {}
        # (tenant, region) → page ids with free slots, claimed by tenant
        self._open: dict[tuple, list[int]] = {}
        self.tenant_pages: dict[Hashable, int] = {}
        # quotas in PAGES (callers convert rows via quota_pages)
        self.tenant_quota_pages: dict[Hashable, int] | None = (
            dict(tenant_quotas) if tenant_quotas else None)
        self.live_rows = 0

    # -- registration -------------------------------------------------------
    def add_region(self, region: Hashable, base: int, n_pages: int) -> None:
        if base % self.page_rows:
            raise ValueError(
                f"region base {base} not aligned to page_rows "
                f"{self.page_rows}")
        pids = []
        for i in range(n_pages):
            pid = len(self.pages)
            self.pages.append(_Page(
                pid, base + i * self.page_rows, region, self.page_rows))
            pids.append(pid)
        # LIFO free list: reversed so lower page ids are taken first
        self._free_pages.setdefault(region, []).extend(reversed(pids))

    @property
    def n_pages(self) -> int:
        return len(self.pages)

    @property
    def n_free_pages(self) -> int:
        return sum(len(v) for v in self._free_pages.values())

    # -- quota accounting ---------------------------------------------------
    def quota_remaining_pages(self, tenant: Hashable) -> int | None:
        """Pages ``tenant`` may still claim (None = unlimited)."""
        if self.tenant_quota_pages is None:
            return None
        quota = self.tenant_quota_pages.get(tenant)
        if quota is None:
            return None
        return max(0, quota - self.tenant_pages.get(tenant, 0))

    def quota_capped_slots(self, tenant: Hashable) -> int | None:
        """Upper bound on slots ``tenant`` can EVER reach from here
        (open-page slack + quota'd fresh pages), growth included. None =
        unbounded."""
        rem = self.quota_remaining_pages(tenant)
        if rem is None:
            return None
        return self._open_slack(tenant) + rem * self.page_rows

    def _open_slack(self, tenant: Hashable) -> int:
        return sum(
            len(self.pages[pid].free)
            for (t, _r), pids in self._open.items() if t == tenant
            for pid in pids)

    # -- allocation ---------------------------------------------------------
    def free_slots_available(self, tenant: Hashable = None,
                             regions: list[Hashable] | None = None) -> int:
        """Slots obtainable WITHOUT growing the pool: the tenant's open
        pages' slack plus unclaimed pages (quota-capped), optionally
        restricted to ``regions``."""
        region_ok = (None if regions is None else set(regions))
        slack = sum(
            len(self.pages[pid].free)
            for (t, r), pids in self._open.items()
            if t == tenant and (region_ok is None or r in region_ok)
            for pid in pids)
        fresh = sum(
            len(pids) for r, pids in self._free_pages.items()
            if region_ok is None or r in region_ok)
        rem = self.quota_remaining_pages(tenant)
        if rem is not None:
            fresh = min(fresh, rem)
        return slack + fresh * self.page_rows

    def take_slot(self, tenant: Hashable = None,
                  regions: list[Hashable] | None = None) -> int:
        """Allocate one slot for ``tenant`` (claiming a fresh page when its
        open pages are full). Raises PageQuotaExceeded / RuntimeError when
        nothing is available — callers ensure_free first."""
        region_ok = (None if regions is None else set(regions))
        for key in list(self._open.keys()):
            t, r = key
            if t != tenant or (region_ok is not None and r not in region_ok):
                continue
            pids = self._open[key]
            while pids:
                page = self.pages[pids[-1]]
                if page.free:
                    return self._take_from(page)
                pids.pop()  # page filled up — no longer open
            del self._open[key]
        page = self._claim_page(tenant, region_ok)
        return self._take_from(page)

    def _claim_page(self, tenant: Hashable, region_ok) -> _Page:
        rem = self.quota_remaining_pages(tenant)
        if rem is not None and rem <= 0:
            raise PageQuotaExceeded(
                f"tenant {tenant!r} page quota "
                f"({self.tenant_quota_pages[tenant]} pages x "
                f"{self.page_rows} rows) exhausted")
        for r, pids in self._free_pages.items():
            if pids and (region_ok is None or r in region_ok):
                page = self.pages[pids.pop()]
                page.tenant = tenant
                page.free = list(range(self.page_rows - 1, -1, -1))
                self.tenant_pages[tenant] = \
                    self.tenant_pages.get(tenant, 0) + 1
                self._open.setdefault((tenant, r), []).append(page.pid)
                return page
        raise RuntimeError(
            "no free pages — pool.ensure_free was not called before "
            "take_slot")

    def _take_from(self, page: _Page) -> int:
        off = page.free.pop()
        page.live += 1
        self.live_rows += 1
        return page.base + off

    def release_slot(self, slot: int) -> None:
        page = self.pages[slot // self.page_rows]
        page.free.append(slot - page.base)
        page.live -= 1
        self.live_rows -= 1
        if page.live == 0:
            # page drained: return to the region free list for ANY tenant
            key = (page.tenant, page.region)
            pids = self._open.get(key)
            if pids is not None:
                try:
                    pids.remove(page.pid)
                except ValueError:
                    pass
                if not pids:
                    del self._open[key]
            self.tenant_pages[page.tenant] = \
                self.tenant_pages.get(page.tenant, 1) - 1
            page.tenant = None
            page.free = []
            self._free_pages.setdefault(page.region, []).append(page.pid)
        else:
            # partially-freed page becomes allocatable again for its tenant
            key = (page.tenant, page.region)
            pids = self._open.setdefault(key, [])
            if page.pid not in pids:
                pids.append(page.pid)

    # -- stats --------------------------------------------------------------
    def stats(self) -> dict:
        live_pages = self.n_pages - self.n_free_pages
        return {
            "page_rows": self.page_rows,
            "pages_total": self.n_pages,
            "pages_free": self.n_free_pages,
            "pages_live": live_pages,
            "live_rows": self.live_rows,
            "occupancy": (self.live_rows / (live_pages * self.page_rows)
                          if live_pages else 0.0),
            "tenants": {
                str(t): n for t, n in self.tenant_pages.items() if n > 0},
        }


class Extent:
    """One device allocation of the pool: ``rows`` vector slots starting at
    global row ``base``. Device arrays are established lazily by the owning
    index (ops/knn.py owns the kernels); once established they are only
    ever updated in place (donated scatters) — never copied or re-uploaded
    on growth."""

    __slots__ = ("base", "rows", "vectors", "valid", "scales", "vsq")

    def __init__(self, base: int, rows: int):
        self.base = base
        self.rows = rows
        self.vectors = None
        self.valid = None
        self.scales = None   # int8 slabs only
        self.vsq = None      # int8 slabs only

    @property
    def established(self) -> bool:
        return self.vectors is not None


_LIVE_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def register_pool(pool: Any) -> None:
    """Register a stats source for :func:`live_paged_stats` — anything
    exposing ``stats()`` with the pool-stats keys (DevicePagePool
    registers itself; the mesh-sharded paged index registers too, its
    extents being sharded arrays rather than flat ones)."""
    _LIVE_POOLS.add(pool)


def live_paged_stats() -> dict | None:
    """Aggregate page-occupancy stats over every live pool in the process —
    the /metrics + dashboard feed (None when no paged store exists)."""
    stats = [p.stats() for p in list(_LIVE_POOLS)]
    if not stats:
        return None
    out = {
        "pools": len(stats),
        # pools may carry different page sizes: report the first (the
        # common case is uniform); occupancy sums per-pool live capacity
        "page_rows": stats[0]["page_rows"],
        "pages_total": 0, "pages_free": 0, "pages_live": 0,
        "live_rows": 0, "capacity_rows": 0, "extents": 0,
        "grow_events": 0, "tenants": {},
    }
    live_capacity = 0
    for st in stats:
        for k in ("pages_total", "pages_free", "pages_live", "live_rows",
                  "grow_events"):
            out[k] += st[k]
        out["capacity_rows"] += st["capacity_rows"]
        out["extents"] += st["extents"]
        live_capacity += st["pages_live"] * st["page_rows"]
        for t, n in st["tenants"].items():
            out["tenants"][t] = out["tenants"].get(t, 0) + n
    out["occupancy"] = (out["live_rows"] / live_capacity
                        if live_capacity else 0.0)
    return out


def _aligned_rows(rows: int, rows_per_page: int) -> int:
    """Extent sizing: page multiple, and a chunk multiple past the chunked
    kernel's threshold (the scan reshapes to (C, chunk, D))."""
    from pathway_tpu.ops.knn import _CHUNK_ROWS, _round_up

    rows = _round_up(max(rows, 1), rows_per_page)
    if rows > _CHUNK_ROWS:
        rows = _round_up(rows, _CHUNK_ROWS)
    return rows


class DevicePagePool:
    """Extent list + page allocator for one logical vector store.

    Growth appends an extent at least as large as everything allocated so
    far (doubling → O(log N) extents → O(log N) per-extent search kernels
    and merge width), sized up to cover large single requests.
    """

    def __init__(self, dim: int, *, reserved_space: int = 0,
                 rows_per_page: int | None = None,
                 tenant_quotas: dict[Hashable, int] | None = None,
                 lock=None):
        from pathway_tpu.ops.knn import planned_capacity

        self.dim = int(dim)
        pr = page_rows(rows_per_page)
        quota_p = (
            {t: quota_pages(rows, pr) for t, rows in tenant_quotas.items()}
            if tenant_quotas else None)
        self.allocator = PageAllocator(pr, quota_p)
        self.extents: list[Extent] = []
        self.grow_events = 0
        # the owning index's lock: every mutation happens under it, and
        # stats() (read by the /metrics & dashboard threads) must too —
        # otherwise the allocator's dict iterations can race ingest
        self._owner_lock = lock
        self._add_extent(_aligned_rows(planned_capacity(reserved_space), pr))
        register_pool(self)

    # -- extents ------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return sum(e.rows for e in self.extents)

    def _add_extent(self, rows: int) -> Extent:
        ext = Extent(self.capacity, rows)
        self.extents.append(ext)
        self.allocator.add_region(
            len(self.extents) - 1, ext.base,
            rows // self.allocator.page_rows)
        return ext

    def grow(self, min_rows: int = 0) -> Extent:
        """Online growth: ONE new extent (device memory established lazily,
        as zeros, on the next flush) — existing extents are not moved,
        copied, re-uploaded or re-quantized."""
        rows = _aligned_rows(max(min_rows, self.capacity),
                             self.allocator.page_rows)
        self.grow_events += 1
        return self._add_extent(rows)

    def ensure_free(self, n: int, tenant: Hashable = None) -> None:
        """Guarantee ``n`` take_slot calls for ``tenant`` succeed."""
        capped = self.allocator.quota_capped_slots(tenant)
        if capped is not None and capped < n:
            raise PageQuotaExceeded(
                f"tenant {tenant!r} needs {n} slots but its page quota "
                f"caps it at {capped} more")
        while self.allocator.free_slots_available(tenant) < n:
            self.grow()

    def reserve_rows(self, n: int, tenant: Hashable = None) -> None:
        """One-shot pre-size for a KNOWN bulk load (snapshot restore,
        bulk re-establish): a single extent covering the whole deficit
        instead of ensure_free's doubling cascade — fewer extents means a
        narrower per-extent search merge afterwards."""
        capped = self.allocator.quota_capped_slots(tenant)
        if capped is not None and capped < n:
            raise PageQuotaExceeded(
                f"tenant {tenant!r} needs {n} slots but its page quota "
                f"caps it at {capped} more")
        deficit = n - self.allocator.free_slots_available(tenant)
        if deficit > 0:
            self.grow(min_rows=deficit)

    # -- slot → extent mapping ---------------------------------------------
    def extent_index_of(self, slot: int) -> int:
        for i, ext in enumerate(self.extents):
            if slot < ext.base + ext.rows:
                return i
        raise IndexError(f"slot {slot} beyond pool capacity {self.capacity}")

    def split_by_extent(self, slots: np.ndarray):
        """Group global slots by extent: yields (extent, local_rows,
        positions) where ``positions`` indexes back into ``slots``. Single-
        extent batches (the common case) yield once with no copy beyond
        the local-offset subtraction."""
        slots = np.asarray(slots, dtype=np.int64)
        for i, ext in enumerate(self.extents):
            in_ext = (slots >= ext.base) & (slots < ext.base + ext.rows)
            if not in_ext.any():
                continue
            pos = np.flatnonzero(in_ext)
            yield ext, (slots[pos] - ext.base), pos

    def touched_page_ids(self) -> frozenset:
        """The page-touch set of a search over this pool *right now*: every
        page of every **established** extent (the per-extent merge scans
        whole extents under their valid masks; extents never established
        hold no rows and are skipped). This is what the semantic result
        cache (engine/result_cache.py) records per entry — an insert into
        a page outside this set at fill time provably landed in device
        memory the entry's candidate scan never read. Callers hold the
        owning index's lock (same contract as every other pool call)."""
        pr = self.allocator.page_rows
        pages: set[int] = set()
        for ext in self.extents:
            if not ext.established:
                continue
            first = ext.base // pr
            pages.update(range(first, first + ext.rows // pr))
        return frozenset(pages)

    def stats(self) -> dict:
        if self._owner_lock is not None:
            with self._owner_lock:
                return self._stats_locked()
        return self._stats_locked()

    def _stats_locked(self) -> dict:
        st = self.allocator.stats()
        st.update({
            "capacity_rows": self.capacity,
            "extents": len(self.extents),
            "grow_events": self.grow_events,
        })
        return st
