"""Runtime snapshot-coverage sanitizer — PWT3xx's execution twin.

The static durability checker
(internals/static_check/durability_check.py) proves about the *source*
that every stateful operator captures what it mutates; this module
asserts it about the *execution*, the same split as PWT2xx vs the lock
sanitizer (engine/locking.py). By default it is completely inert — the
scheduler's snapshot path calls plain ``snapshot_state()``. With
``PATHWAY_SNAPSHOT_SANITIZER=1`` every operator whose class overrides
``snapshot_state`` is *tracked*:

1. **Mutation tracing.** The operator's class is swapped for a generated
   subclass (same ``__name__``/``__qualname__`` — graph fingerprints
   must not change) whose ``__setattr__`` records which attrs were
   rebound since the last snapshot and where (file:line of the writer).
   In-place container mutation never passes through ``__setattr__``, so
   the tracker also fingerprints every attr value at each snapshot and
   diffs against the previous capture — a changed fingerprint is a
   mutation even when no rebind was seen.
2. **Coverage diff.** During each ``snapshot_state()`` call the tracked
   instance records every attr the capture *reads* (via
   ``__getattribute__``). A mutated attr the capture never read is an
   uncovered mutation: the snapshot claims to cover the WAL prefix while
   silently dropping state — :class:`SnapshotCoverageViolation` names
   the operator, the attr, and the mutation site. Deliberately transient
   attrs (per-tick scratch rebuilt on restore) opt out via a class-level
   ``_snapshot_sanitizer_exempt = ("attr", ...)`` tuple.
3. **Shadow round-trip.** On each snapshot the captured state is pushed
   through the restricted unpickler (the same whitelist the write-time
   proof uses) and restored into a deep-copied shadow instance; the
   shadow's re-capture must fingerprint identically. A lossy
   ``restore_state`` (dropped key, un-re-keyed dict) surfaces at
   snapshot time in the writer process instead of as wrong answers in a
   replica hydrated weeks later.

``PATHWAY_SNAPSHOT_SANITIZER=report`` (or ``warn``) logs and records
instead of raising; :func:`violations` returns the findings either way.
"""

from __future__ import annotations

import copy
import hashlib
import logging
import os
import pickle
import sys

from pathway_tpu.engine.locking import create_lock

logger = logging.getLogger(__name__)

__all__ = [
    "SnapshotCoverageViolation", "checked_snapshot", "sanitizer_enabled",
    "track_operator", "violations",
]


def sanitizer_enabled() -> bool:
    """Truthy ``PATHWAY_SNAPSHOT_SANITIZER`` arms tracking. Checked at
    scheduler construction: a run toggles the sanitizer by env, and the
    disabled path keeps plain classes with zero wrapper overhead."""
    return os.environ.get("PATHWAY_SNAPSHOT_SANITIZER", "") \
        .strip().lower() in ("1", "true", "on", "yes", "report", "warn")


def _raise_on_violation() -> bool:
    return os.environ.get("PATHWAY_SNAPSHOT_SANITIZER", "") \
        .strip().lower() not in ("report", "warn")


class SnapshotCoverageViolation(RuntimeError):
    """An operator's snapshot does not faithfully cover its mutated
    state: an attr changed since the last snapshot that
    ``snapshot_state`` never read, or the captured state failed the
    restore round-trip. Restoring this snapshot would silently diverge
    from the writer."""


class _Tracked:
    """Per-operator tracking record (strong op ref pins the id)."""

    __slots__ = ("op", "fps", "write_sites", "reading", "covered")

    def __init__(self, op):
        self.op = op
        self.fps = _attr_fingerprints(op)
        self.write_sites: dict[str, str] = {}
        self.reading = False
        self.covered: set[str] = set()


class _SanitizerState:
    """Process-wide bookkeeping; tests swap in a fresh one via
    :func:`_reset_for_tests`."""

    def __init__(self):
        self.mutex = create_lock("snapshot_sanitizer.state")
        self.violation_log: list[dict] = []
        self.tracked: dict[int, _Tracked] = {}


_STATE = _SanitizerState()


def _reset_for_tests() -> None:
    """Fresh tracking table + violation list (unit tests only)."""
    global _STATE
    _STATE = _SanitizerState()


def violations() -> list[dict]:
    """Violations recorded so far (raise mode records before raising, so
    post-mortems and tests can read the full list either way)."""
    with _STATE.mutex:
        return list(_STATE.violation_log)


def _record_violation(message: str) -> None:
    with _STATE.mutex:
        _STATE.violation_log.append({"message": message})
    if _raise_on_violation():
        raise SnapshotCoverageViolation(message)
    logger.error("snapshot sanitizer: %s", message)


def _fingerprint(value) -> bytes | None:
    """Content digest of an attr value; None when unpicklable (sessions,
    callables, device handles — rebinds of those are still caught by the
    ``__setattr__`` tracer)."""
    try:
        return hashlib.blake2b(
            pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL),
            digest_size=16).digest()
    except Exception:
        return None


def _attr_fingerprints(op) -> dict[str, bytes]:
    out = {}
    for name, value in vars(op).items():
        fp = _fingerprint(value)
        if fp is not None:
            out[name] = fp
    return out


_TRACED: dict[type, type] = {}


def _traced_class(cls: type) -> type:
    traced = _TRACED.get(cls)
    if traced is not None:
        return traced

    def __setattr__(self, name, value):
        rec = _STATE.tracked.get(id(self))
        if rec is not None and not rec.reading:
            f = sys._getframe(1)
            rec.write_sites[name] = \
                f"{f.f_code.co_filename}:{f.f_lineno}"
        super(traced, self).__setattr__(name, value)

    def __getattribute__(self, name):
        rec = _STATE.tracked.get(id(self))
        if rec is not None and rec.reading \
                and not name.startswith("__"):
            rec.covered.add(name)
        return super(traced, self).__getattribute__(name)

    # graph_fingerprint() keys node identity on type(op).__name__ — the
    # traced class must be indistinguishable there
    traced = type(cls.__name__, (cls,), {
        "__setattr__": __setattr__,
        "__getattribute__": __getattribute__,
        "__qualname__": cls.__qualname__,
        "__module__": cls.__module__,
    })
    _TRACED[cls] = traced
    return traced


def track_operator(op):
    """Arm mutation/coverage tracking on ``op`` if its class overrides
    ``snapshot_state`` (stateless operators — base default returning
    None — stay untouched; the static PWT301 covers operators that
    *should* override but don't). Returns ``op``."""
    from pathway_tpu.engine.operators import Operator

    cls = type(op)
    if getattr(cls, "snapshot_state", None) is Operator.snapshot_state:
        return op
    if cls in _TRACED.values():  # already a traced class (re-track)
        with _STATE.mutex:
            _STATE.tracked.setdefault(id(op), _Tracked(op))
        return op
    op.__class__ = _traced_class(cls)
    with _STATE.mutex:
        _STATE.tracked[id(op)] = _Tracked(op)
    return op


def checked_snapshot(op):
    """``op.snapshot_state()`` with coverage + round-trip checking for
    tracked operators; the plain call for everything else. The
    scheduler's snapshot path routes through here whenever the sanitizer
    is enabled."""
    rec = _STATE.tracked.get(id(op))
    if rec is None or rec.op is not op:
        return op.snapshot_state()
    rec.covered = set()
    rec.reading = True
    try:
        state = op.snapshot_state()
    finally:
        rec.reading = False
    cur = _attr_fingerprints(op)
    exempt = set(getattr(type(op), "_snapshot_sanitizer_exempt", ()))
    changed = {a for a, fp in cur.items() if rec.fps.get(a) != fp}
    for a in rec.write_sites:
        if a in cur and a in rec.fps and cur[a] == rec.fps[a]:
            continue  # rebound to an equal value
        changed.add(a)
    if state is not None:
        name = type(op).__name__
        for attr in sorted(changed - rec.covered - exempt):
            site = rec.write_sites.get(attr, "in-place mutation")
            _record_violation(
                f"operator {name}: state attr {attr!r} mutated since "
                f"the last snapshot (at {site}) but snapshot_state "
                f"never read it — a restore from this snapshot "
                f"silently loses the mutation (capture it, or list it "
                f"in {name}._snapshot_sanitizer_exempt if it is "
                f"per-tick scratch)")
        _round_trip_check(op, state)
    rec.fps = cur
    rec.write_sites = {}
    return state


def _round_trip_check(op, state) -> None:
    """Push ``state`` through the restricted unpickler and a shadow
    restore; the shadow's re-capture must fingerprint identically.
    Within one process the volatile keys PWT303 worries about
    (hash()/row_fingerprint) recompute to the same values, so a faithful
    restore is byte-stable here even when it would not be cross-process
    — what this catches is *lossy* capture/restore logic. One blind
    spot: a restore that leaves an attr entirely untouched is invisible,
    because the shadow is a deepcopy of the live instance and already
    holds the value — the static PWT302 key-asymmetry check covers that
    case from the source side."""
    from pathway_tpu.engine.operators import SnapshotUnsupported
    from pathway_tpu.engine.persistence import _safe_loads

    name = type(op).__name__
    try:
        blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:
        _record_violation(
            f"operator {name}: snapshot state is not picklable "
            f"({type(e).__name__}: {e}) — the persistence driver would "
            f"reject this snapshot at write time")
        return
    try:
        state2 = _safe_loads(blob)
    except Exception as e:
        _record_violation(
            f"operator {name}: snapshot state does not survive the "
            f"restricted unpickler ({e}) — restore would reject it; "
            f"extend persistence._SAFE_GLOBALS or capture plain data")
        return
    try:
        shadow = copy.deepcopy(op)
    except Exception:
        return  # shared-handle operators (copy.copy replicas): skip
    try:
        shadow.restore_state(state2)
        recapture = shadow.snapshot_state()
    except SnapshotUnsupported:
        return
    except Exception as e:
        _record_violation(
            f"operator {name}: restore_state raised on its own "
            f"snapshot ({type(e).__name__}: {e}) — recovery from this "
            f"snapshot is impossible")
        return
    if _fingerprint(recapture) != _fingerprint(state):
        _record_violation(
            f"operator {name}: snapshot -> restore -> snapshot is not "
            f"a fixed point — restore_state loses or rewrites captured "
            f"state (check key symmetry and volatile-key re-keying; "
            f"PWT302/PWT303 are the static twins of this finding)")
