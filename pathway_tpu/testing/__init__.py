"""Testing utilities shipped with the package: the deterministic
fault-injection harness (``pathway_tpu.testing.faults``) used by the
fault-tolerance suite and available to downstream users hardening their
own pipelines."""
