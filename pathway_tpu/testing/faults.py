"""Deterministic fault injection for the streaming runtime.

Two complementary mechanisms (reference: the wordcount kill-and-recover
harness, integration_tests/wordcount/test_recovery.py, generalized into
named failpoints like the reference engine's test-only error hooks):

1. **Fault points** — named hooks compiled into runtime hot spots
   (``faults.hit("persistence.fsync")`` in engine/persistence.py,
   ``faults.hit("cluster.exchange.delay")`` in engine/multiproc.py).
   Unarmed they are a dict lookup against an empty registry; a test arms
   them with an action (:class:`FailNTimes`, :class:`Delay`) to inject a
   failure at an exact, reproducible moment: an fsync that dies
   mid-commit, a torn append, a peer that delays a tick exchange.

   Watermark-durability boundaries (PR 8) each have a point, so the
   crash/restart sweep can land on every edge of the resolved-prefix
   commit protocol: ``bridge.leg.exec`` (the device leg itself fails,
   with N ticks committed and M legs in flight), ``bridge.leg.resolved``
   (crash between the leg's work retiring and the watermark advancing —
   work done, durability frontier frozen), ``persistence.commit``
   (crash between reading the watermark and the durable append),
   ``persistence.append`` / ``persistence.append.torn`` /
   ``persistence.fsync`` (inside the append; transient failures here are
   retried with backoff — arm more failures than
   ``PATHWAY_PERSISTENCE_WRITE_RETRIES`` to exhaust the budget), and
   ``persistence.s3.put`` (the object-store upload).

   Snapshot/compaction boundaries (PR 10) extend the sweep to the
   operator-state checkpoint protocol: ``persistence.snapshot.write``
   (crash before the snapshot state file becomes durable — the previous
   generation plus the full WAL must recover), ``persistence.compact.
   truncate`` (crash between the new snapshot generation going durable
   and the WAL prefix truncation — covered records still in the WAL must
   be ignored, not replayed twice), and ``persistence.append.corrupt``
   (arm with :class:`CorruptPayload` to bit-flip a record's payload
   after its CRC was computed — a mid-log corruption the next ``_scan``
   must detect and truncate at, loudly, instead of feeding garbage to
   the unpickler).

   Write-path failover boundaries (PR 18) cover the promotion window:
   ``replica.promote.crash`` (engine/streaming.py ``_execute_promotion``
   — fires after the fencing epoch is bumped but BEFORE connector
   readers start, i.e. a candidate dying mid-promotion; the router must
   elect the next survivor, whose own claim bumps the epoch again, and
   zero acknowledged writes may be lost), ``persistence.epoch.claim``
   (inside the fsynced epoch-manifest write — a torn manifest must
   leave the previous epoch readable) and ``router.control.partition``
   (engine/multiproc.py ``send_control_frame``/``recv_control_frame`` —
   while armed, control frames are silently dropped in BOTH directions:
   heartbeats vanish, promote commands are lost, and the router's
   heartbeat-staleness detector, not socket EOF, has to drive the
   election).

2. **Faulty sources** — ``ConnectorSubject`` doubles with scripted crash
   schedules (:func:`flaky_subject` raises after the Nth entry on the
   first K attempts; :func:`hanging_subject` stops producing while
   claiming liveness) driving the supervisor's restart/escalation/watchdog
   paths end to end.

Always ``reset()`` (or use the ``arm`` context manager) after a test —
armed points are process-global.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Iterable


class InjectedFault(RuntimeError):
    """The exception raised by armed fault points and scripted sources —
    a distinct type so tests can assert the *injected* failure surfaced,
    not an incidental one."""


# ---------------------------------------------------------------------------
# fault points
# ---------------------------------------------------------------------------

_registry: dict[str, Callable] = {}
_lock = threading.Lock()


def hit(point: str, **ctx) -> None:
    """Runtime-side hook: no-op unless a test armed ``point``."""
    action = _registry.get(point)
    if action is not None:
        action(point, ctx)


def armed(point: str) -> bool:
    """Whether ``point`` currently has an action — lets hot paths skip
    preparing fault context (e.g. the mutable payload copy
    ``persistence.append.corrupt`` needs) when nothing is armed."""
    return point in _registry


def arm_point(point: str, action: Callable) -> None:
    """Arm ``point`` with ``action(point, ctx)`` — raises to inject a
    failure, sleeps to inject a delay, or anything else."""
    with _lock:
        _registry[point] = action


def disarm(point: str) -> None:
    with _lock:
        _registry.pop(point, None)


def reset() -> None:
    """Disarm every fault point (call from test teardown)."""
    with _lock:
        _registry.clear()


@contextlib.contextmanager
def arm(point: str, action: Callable):
    """``with faults.arm("persistence.fsync", faults.FailNTimes(1)): ...``"""
    arm_point(point, action)
    try:
        yield action
    finally:
        disarm(point)


class FailNTimes:
    """Raise on the first ``n`` hits, then pass (a transient failure)."""

    def __init__(self, n: int = 1, exc: type[Exception] = InjectedFault):
        self.n = n
        self.exc = exc
        self.hits = 0

    def __call__(self, point: str, ctx: dict) -> None:
        self.hits += 1
        if self.hits <= self.n:
            raise self.exc(f"injected fault at {point!r} (hit {self.hits})")


class FailOnHit:
    """Raise on exactly the ``k``-th hit (1-based), pass otherwise."""

    def __init__(self, k: int, exc: type[Exception] = InjectedFault):
        self.k = k
        self.exc = exc
        self.hits = 0

    def __call__(self, point: str, ctx: dict) -> None:
        self.hits += 1
        if self.hits == self.k:
            raise self.exc(f"injected fault at {point!r} (hit {self.hits})")


class CorruptPayload:
    """Flip one byte of the mutable ``payload`` bytearray passed in the
    fault context, on the ``k``-th hit (1-based). Used with
    ``persistence.append.corrupt``: the CRC was computed on the clean
    payload, so the written record is a mid-log corruption the next scan
    must detect."""

    def __init__(self, k: int = 1, byte_index: int = 0):
        self.k = k
        self.byte_index = byte_index
        self.hits = 0
        self.corrupted = 0

    def __call__(self, point: str, ctx: dict) -> None:
        self.hits += 1
        payload = ctx.get("payload")
        if self.hits == self.k and payload:
            i = self.byte_index % len(payload)
            payload[i] ^= 0xFF
            self.corrupted += 1


class Delay:
    """Sleep ``seconds`` on each of the first ``times`` hits (None = every
    hit) — e.g. a cluster peer delaying a tick exchange."""

    def __init__(self, seconds: float, times: int | None = None):
        self.seconds = seconds
        self.times = times
        self.hits = 0

    def __call__(self, point: str, ctx: dict) -> None:
        self.hits += 1
        if self.times is None or self.hits <= self.times:
            time.sleep(self.seconds)


# ---------------------------------------------------------------------------
# scripted faulty sources (pw.io.python ConnectorSubject doubles)
# ---------------------------------------------------------------------------

def flaky_subject(rows: Iterable[dict], *, fail_after: int,
                  fail_attempts: int = 1, delay_s: float = 0.0):
    """A ``ConnectorSubject`` that re-emits ``rows`` from the start on each
    (re)start attempt and, on the first ``fail_attempts`` attempts, raises
    :class:`InjectedFault` after emitting ``fail_after`` rows. Attempt
    ``fail_attempts`` (0-based) onward emits everything and finishes —
    "reader raises after N entries / raises on the Kth restart" in one
    deterministic schedule. ``fail_attempts=-1`` fails on every attempt
    (retries can never succeed). ``delay_s`` paces emission so commit
    ticks land between rows (exercising mid-stream checkpoints)."""
    from pathway_tpu.io.python import ConnectorSubject

    rows = list(rows)

    class _Flaky(ConnectorSubject):
        attempts = 0  # completed start attempts so far

        def run(self) -> None:
            attempt = type(self).attempts
            type(self).attempts = attempt + 1
            failing = fail_attempts < 0 or attempt < fail_attempts
            for i, values in enumerate(rows):
                if failing and i == fail_after:
                    raise InjectedFault(
                        f"reader crash after {fail_after} entries "
                        f"(attempt {attempt})")
                if delay_s:
                    time.sleep(delay_s)
                self.next(**values)
            if failing and fail_after >= len(rows):
                raise InjectedFault(
                    f"reader crash at end of stream (attempt {attempt})")

    return _Flaky()


def hanging_subject(rows: Iterable[dict], *, hang_attempts: int = -1):
    """A ``ConnectorSubject`` that emits ``rows`` and then hangs — thread
    alive, session open, no pushes and no ``sleep()`` heartbeat — until
    the runtime requests stop. The watchdog's hung-reader case. With
    ``hang_attempts >= 0``, attempts past that count finish cleanly
    instead (proving watchdog-triggered restart heals the pipeline)."""
    from pathway_tpu.io.python import ConnectorSubject

    rows = list(rows)

    class _Hanging(ConnectorSubject):
        attempts = 0

        def run(self) -> None:
            attempt = type(self).attempts
            type(self).attempts = attempt + 1
            for values in rows:
                self.next(**values)
            if 0 <= hang_attempts <= attempt:
                return  # healed: finish as end-of-stream
            # hang while claiming liveness: plain sleep, never the
            # session's heartbeating sleep(); still honors stop so the
            # abandoned thread exits instead of leaking
            while not self._session.stop_requested:
                time.sleep(0.01)

    return _Hanging()
