"""``pathway-tpu`` command line interface
(reference: python/pathway/cli.py:53-280 — spawn / replay / spawn-from-env).

``spawn -t T -n N program.py`` forks N processes of the user program with
``PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/RUN_ID`` set — each
process hosts its shard of the device mesh (the reference's timely cluster
topology, re-aimed at multi-host TPU). ``replay`` re-runs a program against
a recorded snapshot directory with batch/speedrun timing, optionally
continuing live afterwards. Recording/replay wiring rides the persistence
env vars consumed by ``pw.run`` (internals/run.py)."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import uuid

import click

import pathway_tpu as pw


def _plural(n: int, singular: str, plural: str) -> str:
    return f"{n} {singular if n == 1 else plural}"


def spawn_program(*, threads: int, processes: int, first_port: int,
                  program: str, arguments: tuple[str, ...], env_base: dict):
    """Fork N processes of the user program, each owning T logical workers
    (reference: cli.py:53-110,166 — PATHWAY_THREADS/PROCESSES/PROCESS_ID/
    FIRST_PORT envs; processes cluster over TCP at FIRST_PORT+i,
    engine/multiproc.py)."""
    click.echo(
        f"Preparing {_plural(processes, 'process', 'processes')} "
        f"({_plural(processes * threads, 'total worker', 'total workers')})",
        err=True)
    run_id = str(uuid.uuid4())
    handles = []
    for pid in range(processes):
        env = dict(env_base)
        env["PATHWAY_THREADS"] = str(threads)
        env["PATHWAY_PROCESSES"] = str(processes)
        env["PATHWAY_FIRST_PORT"] = str(first_port)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        env["PATHWAY_RUN_ID"] = run_id
        handles.append(subprocess.Popen([program, *arguments], env=env))
    rc = 0
    try:
        for handle in handles:
            rc = handle.wait() or rc
    finally:
        for handle in handles:
            if handle.poll() is None:
                handle.terminate()
    sys.exit(rc)


@click.group()
@click.version_option(version=pw.__version__, prog_name="pathway-tpu")
def cli() -> None:
    pass


_spawn_opts = [
    click.option("-t", "--threads", metavar="N", type=int, default=1,
                 help="number of threads per process"),
    click.option("-n", "--processes", metavar="N", type=int, default=1,
                 help="number of processes"),
    click.option("--first-port", type=int, metavar="PORT", default=10000,
                 help="first port to use for communication"),
]


def _apply(opts, f):
    for opt in reversed(opts):
        f = opt(f)
    return f


@cli.command(context_settings={"allow_interspersed_args": False,
                               "show_default": True})
@click.option("--record", is_flag=True,
              help="record data from connectors while running")
@click.option("--record-path", type=str, default="record",
              help="directory in which recording is stored")
@click.argument("program")
@click.argument("arguments", nargs=-1)
@click.pass_context
def spawn(ctx, record, record_path, program, arguments,
          threads=1, processes=1, first_port=10000):
    env = os.environ.copy()
    if record:
        env["PATHWAY_REPLAY_STORAGE"] = record_path
        env["PATHWAY_SNAPSHOT_ACCESS"] = "record"
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    spawn_program(threads=threads, processes=processes,
                  first_port=first_port, program=program,
                  arguments=arguments, env_base=env)


spawn = _apply(_spawn_opts, spawn)


@cli.command(context_settings={"allow_interspersed_args": False,
                               "show_default": True})
@click.option("--record-path", type=str, default="record",
              help="directory in which recording is stored")
@click.option("--mode",
              type=click.Choice(["batch", "speedrun"], case_sensitive=False),
              help="mode of replaying data")
@click.option("--continue", "continue_after_replay", is_flag=True,
              help="continue with realtime data after the recording replays")
@click.argument("program")
@click.argument("arguments", nargs=-1)
def replay(record_path, mode, continue_after_replay, program, arguments,
           threads=1, processes=1, first_port=10000):
    env = os.environ.copy()
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_SNAPSHOT_ACCESS"] = "replay"
    if mode:
        env["PATHWAY_PERSISTENCE_MODE"] = (
            "batch" if mode.lower() == "batch" else "speedrun_replay")
    if continue_after_replay:
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    spawn_program(threads=threads, processes=processes,
                  first_port=first_port, program=program,
                  arguments=arguments, env_base=env)


replay = _apply(_spawn_opts, replay)


@cli.command()
@click.option("--strict", is_flag=True,
              help="treat warnings as errors (info stays informational)")
@click.option("--require-pipeline", is_flag=True,
              help="fail scripts that build no tables and register no "
                   "sinks (catches graphs hidden behind __main__ guards)")
@click.option("--tpu-mesh", "tpu_mesh", metavar="DATAxMODEL", default=None,
              help="analyze against a hypothetical device topology "
                   "(e.g. 4x2) — arms the PWT1xx sharding/placement "
                   "checks without owning the hardware")
@click.option("--json", "as_json", is_flag=True,
              help="emit machine-readable diagnostics (code, severity, "
                   "file, line, message) on stdout for CI annotation; "
                   "exit-code semantics unchanged")
@click.option("--concurrency", "concurrency", is_flag=True,
              help="run the PWT2xx concurrency lint instead: an AST pass "
                   "over the given source files/directories (thread "
                   "inventory, lock inventory, lock-order graph) — "
                   "nothing is imported or executed")
@click.option("--durability", "durability", is_flag=True,
              help="run the PWT3xx durability lint instead: an AST pass "
                   "over the given source files/directories (snapshot "
                   "capture/restore contracts, atomic persistence writes, "
                   "restricted unpickling) — nothing is imported or "
                   "executed")
@click.option("--perf", "perf", is_flag=True,
              help="run the PWT4xx device-discipline lint instead: an AST "
                   "pass over the given source files/directories "
                   "(recompile zoos, hidden host-device syncs, per-row "
                   "dispatch, donation/residency discipline, warmup "
                   "registry coverage) — nothing is imported or executed")
@click.option("--all", "all_families", is_flag=True,
              help="run every check family in one pass: script analysis "
                   "(PWT0xx expression + PWT1xx shard) over .py file "
                   "arguments, source lints (PWT2xx concurrency + PWT3xx "
                   "durability + PWT4xx perf) over directory arguments; "
                   "--json emits a versioned per-family payload and the "
                   "exit code is a bitmask (expression=1, shard=2, "
                   "concurrency=4, durability=8, perf=16)")
@click.option("--list-waivers", "list_waivers", is_flag=True,
              help="report every inline 'pwt-ok' waiver under the given "
                   "source trees (code, file:line, justification) instead "
                   "of linting; --json emits a machine-readable list for "
                   "CI audit artifacts")
@click.argument("paths", nargs=-1, required=True)
def check(paths, strict, require_pipeline, tpu_mesh, as_json, concurrency,
          durability, perf, all_families, list_waivers):
    """Statically analyze pipeline scripts without running them.

    Imports each script (or every ``*.py`` under a directory) with
    ``pw.run`` disabled, collects the Table plan DAG it builds, and runs
    the static analyzer (internals/static_check/) over it. Scripts are
    imported with ``__name__ == "__pathway_check__"``, so pipelines built
    only under ``if __name__ == "__main__":`` are skipped (reported as
    "no pipeline collected"; an error under ``--require-pipeline``) — add
    an ``if __name__ == "__pathway_check__":`` branch building the graph
    with placeholder inputs to have it checked. Exits nonzero on any
    error-severity diagnostic.

    With ``--concurrency``, ``--durability`` or ``--perf`` the paths are
    treated as SOURCE trees instead: the PWT2xx concurrency lint (thread
    inventory, lock inventory, lock-order graph), the PWT3xx durability
    lint (snapshot coverage, capture/restore symmetry, atomic
    persistence) or the PWT4xx device-discipline lint (recompile zoos,
    hidden host-device syncs, donation/residency discipline) — all
    internals/static_check/ AST passes — run over them without importing
    anything; ``--json`` adds the inventories to the payload.

    ``--all`` runs every family in one invocation; ``--list-waivers``
    audits inline ``pwt-ok`` suppressions instead of linting."""
    import json as _json
    import pathlib

    from pathway_tpu.internals.static_check import (Severity,
                                                    parse_mesh_spec)

    modes = [name for flag, name in (
        (concurrency, "--concurrency"), (durability, "--durability"),
        (perf, "--perf"), (all_families, "--all"),
        (list_waivers, "--list-waivers"),
    ) if flag]
    if len(modes) > 1:
        raise click.UsageError(
            f"{' and '.join(modes)} are mutually exclusive")
    if modes and (tpu_mesh is not None or require_pipeline):
        raise click.UsageError(
            f"{modes[0]} does not compose with "
            "--tpu-mesh/--require-pipeline")
    if concurrency:
        _check_concurrency_cli(paths, strict=strict, as_json=as_json)
        return
    if durability:
        _check_durability_cli(paths, strict=strict, as_json=as_json)
        return
    if perf:
        _check_perf_cli(paths, strict=strict, as_json=as_json)
        return
    if list_waivers:
        _list_waivers_cli(paths, as_json=as_json)
        return
    if all_families:
        _check_all_cli(paths, strict=strict, as_json=as_json)
        return

    mesh = None
    if tpu_mesh is not None:
        try:
            mesh = parse_mesh_spec(tpu_mesh)
        except ValueError as e:
            raise click.UsageError(str(e))

    scripts: list[pathlib.Path] = []
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            # directory mode only gates pipeline entry points: helper
            # modules (_*.py, __init__.py) and hidden dirs (.venv, .git)
            # are skipped — pass a file path explicitly to force a check
            scripts.extend(
                f for f in sorted(path.rglob("*.py"))
                if not f.name.startswith("_")
                and not any(part.startswith(".")
                            for part in f.relative_to(path).parts))
        elif path.suffix == ".py":
            scripts.append(path)
        else:
            raise click.UsageError(f"not a python script or directory: {p}")
    if not scripts:
        raise click.UsageError("no python scripts found under given paths")

    n_errors = 0
    json_out: list[dict] = []
    for script in scripts:
        diagnostics, collected = _collect_and_check(script, mesh=mesh)
        bad = [d for d in diagnostics
               if d.severity is Severity.ERROR
               or (strict and d.severity is Severity.WARNING)]
        if not collected and require_pipeline and not bad:
            n_errors += 1
            click.echo(f"[FAIL] {script} — no pipeline collected "
                       "(--require-pipeline)", err=True)
        elif not collected and not bad:
            click.echo(f"[ok] {script} — no pipeline collected", err=True)
        else:
            n_errors += len(bad)
            status = "FAIL" if bad else "ok"
            click.echo(f"[{status}] {script} — "
                       f"{len(diagnostics)} diagnostic(s)", err=True)
        for d in diagnostics:
            if as_json:
                json_out.append({"script": str(script), **d.to_dict()})
            else:
                click.echo(str(d))
    if as_json:
        click.echo(_json.dumps(json_out, indent=2))
    if n_errors:
        click.echo(f"static check failed: {n_errors} blocking "
                   f"diagnostic(s)", err=True)
        sys.exit(1)


def _check_concurrency_cli(paths, *, strict: bool, as_json: bool) -> None:
    """``check --concurrency``: the PWT2xx source-level lint. Exit-code
    semantics mirror the pipeline check — nonzero on any error-severity
    diagnostic (warnings too under ``--strict``). ``--json`` emits the
    diagnostics plus the thread/lock inventory for CI artifacts."""
    import json as _json

    from pathway_tpu.internals.static_check import (Severity,
                                                    check_concurrency,
                                                    concurrency_inventory)
    from pathway_tpu.internals.static_check.concurrency_check import \
        build_corpus

    try:
        corpus = build_corpus(paths)  # one parse serves check + inventory
        diagnostics = check_concurrency(paths, corpus=corpus)
    except ValueError as e:
        raise click.UsageError(str(e))
    bad = [d for d in diagnostics
           if d.severity is Severity.ERROR
           or (strict and d.severity is Severity.WARNING)]
    if as_json:
        payload = {
            "diagnostics": [d.to_dict() for d in diagnostics],
            "inventory": concurrency_inventory(paths, corpus=corpus),
        }
        click.echo(_json.dumps(payload, indent=2))
    else:
        for d in diagnostics:
            click.echo(str(d))
    status = "FAIL" if bad else "ok"
    click.echo(f"[{status}] concurrency check over {', '.join(paths)} — "
               f"{len(diagnostics)} diagnostic(s)", err=True)
    if bad:
        click.echo(f"concurrency check failed: {len(bad)} blocking "
                   f"diagnostic(s)", err=True)
        sys.exit(1)


def _check_durability_cli(paths, *, strict: bool, as_json: bool) -> None:
    """``check --durability``: the PWT3xx source-level lint. Same
    exit-code semantics as ``--concurrency``; ``--json`` adds the
    stateful-operator/fault-point inventory for CI artifacts."""
    import json as _json

    from pathway_tpu.internals.static_check import (Severity,
                                                    check_durability,
                                                    durability_inventory)
    from pathway_tpu.internals.static_check.durability_check import \
        build_corpus

    try:
        corpus = build_corpus(paths)  # one parse serves check + inventory
        diagnostics = check_durability(paths, corpus=corpus)
    except ValueError as e:
        raise click.UsageError(str(e))
    bad = [d for d in diagnostics
           if d.severity is Severity.ERROR
           or (strict and d.severity is Severity.WARNING)]
    if as_json:
        payload = {
            "diagnostics": [d.to_dict() for d in diagnostics],
            "inventory": durability_inventory(paths, corpus=corpus),
        }
        click.echo(_json.dumps(payload, indent=2))
    else:
        for d in diagnostics:
            click.echo(str(d))
    status = "FAIL" if bad else "ok"
    click.echo(f"[{status}] durability check over {', '.join(paths)} — "
               f"{len(diagnostics)} diagnostic(s)", err=True)
    if bad:
        click.echo(f"durability check failed: {len(bad)} blocking "
                   f"diagnostic(s)", err=True)
        sys.exit(1)


def _check_perf_cli(paths, *, strict: bool, as_json: bool) -> None:
    """``check --perf``: the PWT4xx device-discipline lint. Same
    exit-code semantics as ``--concurrency``; ``--json`` adds the jit /
    hot-unit / warmup-registry inventory for CI artifacts."""
    import json as _json

    from pathway_tpu.internals.static_check import (Severity, check_perf,
                                                    perf_inventory)
    from pathway_tpu.internals.static_check.durability_check import \
        build_corpus

    try:
        corpus = build_corpus(paths)  # one parse serves check + inventory
        diagnostics = check_perf(paths, corpus=corpus)
    except ValueError as e:
        raise click.UsageError(str(e))
    bad = [d for d in diagnostics
           if d.severity is Severity.ERROR
           or (strict and d.severity is Severity.WARNING)]
    if as_json:
        payload = {
            "diagnostics": [d.to_dict() for d in diagnostics],
            "inventory": perf_inventory(paths, corpus=corpus),
        }
        click.echo(_json.dumps(payload, indent=2))
    else:
        for d in diagnostics:
            click.echo(str(d))
    status = "FAIL" if bad else "ok"
    click.echo(f"[{status}] perf check over {', '.join(paths)} — "
               f"{len(diagnostics)} diagnostic(s)", err=True)
    if bad:
        click.echo(f"perf check failed: {len(bad)} blocking "
                   f"diagnostic(s)", err=True)
        sys.exit(1)


def _list_waivers_cli(paths, *, as_json: bool) -> None:
    """``check --list-waivers``: audit inline ``pwt-ok`` suppressions.
    Always exits 0 — waivers are sanctioned, the point is visibility
    (the CI durability-lint job archives the JSON as an audit artifact)."""
    import json as _json

    from pathway_tpu.internals.static_check import (render_waivers,
                                                    scan_waivers)

    try:
        waivers = scan_waivers(paths)
    except ValueError as e:
        raise click.UsageError(str(e))
    if as_json:
        click.echo(_json.dumps(waivers, indent=2))
    elif waivers:
        click.echo(render_waivers(waivers))
    click.echo(f"[ok] {_plural(len(waivers), 'waiver', 'waivers')} under "
               f"{', '.join(paths)}", err=True)


# ``check --all`` exit code is a bitmask so CI can tell which family
# regressed from the code alone (and --json mirrors it as "exit_code")
_FAMILY_BITS = {"expression": 1, "shard": 2, "concurrency": 4,
                "durability": 8, "perf": 16}


def _defer_pwt105(shard_diags: list, trees) -> list:
    """PWT105 defers to PWT402 when both families run in one invocation:
    drop PWT105 findings whose UDF *definition* (the related trace
    shard_check attaches) lives under a tree the PWT4xx pass scanned —
    the wider device-path lint already covers that source, and keeping
    both would double-report every sync site."""
    import pathlib

    roots = [pathlib.Path(t).resolve() for t in trees]

    def _covered(d) -> bool:
        if d.code != "PWT105" or not d.related:
            return False
        f = pathlib.Path(d.related[0].file_name).resolve()
        return any(root == f or root in f.parents for root in roots)

    return [d for d in shard_diags if not _covered(d)]


def _check_all_cli(paths, *, strict: bool, as_json: bool) -> None:
    """``check --all``: every family in one invocation. ``.py`` file
    arguments get the script analysis (PWT0xx expression / PWT1xx shard,
    split per diagnostic code); directory arguments get the source lints
    (PWT2xx concurrency, PWT3xx durability, PWT4xx perf). The JSON
    payload is versioned (``schema_version``) so CI consumers can evolve
    with it."""
    import json as _json
    import pathlib

    from pathway_tpu.internals.static_check import (Severity,
                                                    check_concurrency,
                                                    check_durability,
                                                    check_perf)

    scripts = [p for p in paths if pathlib.Path(p).suffix == ".py"]
    trees = [p for p in paths if p not in scripts]
    for p in trees:
        if not pathlib.Path(p).is_dir():
            raise click.UsageError(
                f"not a python script or directory: {p}")

    families: dict[str, list] = {
        "expression": [], "shard": [], "concurrency": [],
        "durability": [], "perf": []}
    for script in scripts:
        diagnostics, _collected = _collect_and_check(
            pathlib.Path(script), mesh=None)
        for d in diagnostics:
            fam = "shard" if d.code.startswith("PWT1") else "expression"
            families[fam].append(d)
    if trees:
        try:
            families["concurrency"] = check_concurrency(trees)
            families["durability"] = check_durability(trees)
            families["perf"] = check_perf(trees)
        except ValueError as e:
            raise click.UsageError(str(e))
        families["shard"] = _defer_pwt105(families["shard"], trees)

    exit_code = 0
    for fam, diagnostics in families.items():
        bad = [d for d in diagnostics
               if d.severity is Severity.ERROR
               or (strict and d.severity is Severity.WARNING)]
        if bad:
            exit_code |= _FAMILY_BITS[fam]
        if not as_json:
            for d in diagnostics:
                click.echo(str(d))
        click.echo(f"[{'FAIL' if bad else 'ok'}] {fam} — "
                   f"{len(diagnostics)} diagnostic(s)", err=True)
    if as_json:
        click.echo(_json.dumps({
            # v2: adds the "perf" family (PWT4xx, exit bit 16) and the
            # PWT105→PWT402 deference over shared trees
            "schema_version": 2,
            "families": {fam: [d.to_dict() for d in diagnostics]
                         for fam, diagnostics in families.items()},
            "exit_code": exit_code,
        }, indent=2))
    if exit_code:
        click.echo(f"static check failed (family bitmask {exit_code})",
                   err=True)
        sys.exit(exit_code)


def _collect_and_check(script, mesh=None):
    """Import one script in collect-only mode and analyze its graph.

    Returns ``(diagnostics, collected)`` where ``collected`` is False when
    the script built no tables and registered no sinks — indistinguishable
    from "clean" otherwise, which would make directory gates vacuous."""
    import runpy

    from pathway_tpu.internals import run as _run_module
    from pathway_tpu.internals.parse_graph import G
    from pathway_tpu.internals.static_check import Diagnostic, analyze

    def _collect_only(**kwargs):
        return None

    def _register_as_sink(table, **kwargs):
        # debug prints count as the pipeline's intended outputs, but must
        # not execute the engine during a static check
        G.add_output(lambda runner: None, table=table, sink="debug")

    patched = [(pw, "run", _collect_only), (pw, "run_all", _collect_only),
               (_run_module, "run", _collect_only),
               (_run_module, "run_all", _collect_only),
               (pw.debug, "compute_and_print", _register_as_sink),
               (pw.debug, "compute_and_print_update_stream",
                _register_as_sink)]
    saved = [getattr(mod, name) for mod, name, _ in patched]

    # the graph registry holds Tables only weakly; pin every table the
    # script constructs so the DAG survives until analyze() even if the
    # module globals are gone (e.g. the script calls sys.exit(0))
    keep_alive: list = []
    _real_register = G.register_table

    def _register_pinned(table):
        keep_alive.append(table)
        _real_register(table)

    G.clear()
    script_dir = os.path.dirname(os.path.abspath(str(script)))
    sys.path.insert(0, script_dir)
    G.register_table = _register_pinned
    # scripts in one directory may share helper modules with import-time
    # side effects; drop helpers this script imports afterwards so every
    # script's collection runs against a cold import cache
    modules_before = set(sys.modules)

    def _is_local_helper(name: str) -> bool:
        f = getattr(sys.modules.get(name), "__file__", None)
        return bool(f) and os.path.abspath(f).startswith(
            script_dir + os.sep)
    try:
        for mod, name, stub in patched:
            setattr(mod, name, stub)
        try:
            runpy.run_path(str(script), run_name="__pathway_check__")
        except KeyboardInterrupt:
            raise  # Ctrl-C must abort the whole check, not log a PWT000
        except SystemExit as e:
            if e.code not in (None, 0):
                return [Diagnostic(
                    code="PWT000",
                    message="script exited with status "
                            f"{e.code} during collection")], True
            # clean exit: analyze what was collected
        except BaseException as e:  # noqa: BLE001 — report, do not crash
            return [Diagnostic(
                code="PWT000",
                message=f"script failed during collection: {e!r}")], True
        collected = bool(G.tables() or G.outputs)
        from pathway_tpu.engine.qos import qos_enabled_from_env

        # PWT013 arming from the CLI: the script's run-time qos= argument
        # is unknowable here, but an explicit PATHWAY_QOS decision in the
        # environment (1 = enabled, 0 = the documented waiver) must be
        # honored the same way pw.run honors it
        diagnostics = analyze(graph=G, mesh=mesh,
                              qos_enabled=qos_enabled_from_env())
        return diagnostics, collected
    finally:
        for (mod, name, _), fn in zip(patched, saved):
            setattr(mod, name, fn)
        del G.register_table  # drop the instance shadow of the class method
        sys.path.remove(script_dir)
        for name in set(sys.modules) - modules_before:
            # framework/third-party modules stay cached: re-executing them
            # repeats registration side effects (and C extensions such as
            # jaxlib do not survive partial re-import at all)
            if _is_local_helper(name):
                del sys.modules[name]
        G.clear()


@cli.command("trace-merge")
@click.option("--out", "out_path", type=str, default=None,
              help="where to write the merged trace "
                   "(default: <dir>/fleet_trace.json)")
@click.argument("paths", nargs=-1, required=True)
def trace_merge(paths, out_path):
    """Merge per-process Chrome trace files into ONE clock-aligned
    fleet timeline (engine/fleet_observability.py).

    PATHS are trace JSON files — or directories scanned for ``*.json``
    files that look like Chrome traces (a ``traceEvents`` list). Each
    process's ``pathway_meta`` block (written by the flight recorder:
    pid, role, process label, monotonic↔wall clock anchor) places its
    events on the shared wall-clock timeline; request ids that appear in
    several processes get cross-process flow arrows, so a failover
    renders as an arrow from the router into the rescuing replica's
    track. The merged file opens directly in Perfetto."""
    import pathlib

    from pathway_tpu.engine.fleet_observability import merge_traces
    from pathway_tpu.engine.flight_recorder import atomic_write_json

    files: list[pathlib.Path] = []
    first_dir: pathlib.Path | None = None
    for p in paths:
        path = pathlib.Path(p)
        if path.is_dir():
            if first_dir is None:
                first_dir = path
            files.extend(sorted(path.glob("*.json")))
        elif path.is_file():
            files.append(path)
        else:
            raise click.UsageError(f"no such file or directory: {p}")
    if out_path is None:
        out_path = str((first_dir or pathlib.Path("."))
                       / "fleet_trace.json")
    payloads = []
    for f in files:
        if os.path.abspath(str(f)) == os.path.abspath(out_path):
            continue  # re-running over a dir must not merge its own output
        try:
            with open(f) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        if isinstance(data, dict) and isinstance(
                data.get("traceEvents"), list):
            payloads.append(data)
        else:
            click.echo(f"[skip] {f} — not a Chrome trace payload",
                       err=True)
    if not payloads:
        raise click.UsageError(
            "no Chrome trace payloads found under the given paths "
            "(run with PATHWAY_TRACE_PATH set on each process, or point "
            "at the router's /fleet/trace output)")
    merged = merge_traces(payloads)
    atomic_write_json(out_path, merged)
    fleet = merged["pathway_fleet"]
    click.echo(
        f"merged {len(payloads)} process trace(s) -> {out_path}: "
        f"{len(merged['traceEvents'])} events, "
        f"{len(fleet['cross_process_request_ids'])} request id(s) "
        f"spanning processes "
        f"({', '.join(p['role'] + ':' + p['process'] for p in fleet['processes'])})",
        err=True)


@cli.command("profdiff")
@click.option("--json", "as_json", is_flag=True,
              help="emit the full structured diff as JSON on stdout")
@click.argument("baseline", type=str)
@click.argument("flagged", type=str)
def profdiff(baseline, flagged, as_json):
    """Name the dominant frame/kernel delta between two profiled runs.

    BASELINE and FLAGGED are ``bench.py --profile`` artifacts
    (BENCH_*.json with embedded ``profile`` epochs) or bare profile
    epochs; the comparison (engine/profiler.py diff_profiles) ranks
    per-kernel-family device-ms-per-dispatch deltas and per-host-frame
    sample-share deltas, so a flagged ``--check-regression`` run gets a
    culprit name instead of just a number."""
    from pathway_tpu.engine.profiler import diff_profiles

    docs = []
    for p in (baseline, flagged):
        try:
            with open(p) as f:
                docs.append(json.load(f))
        except (OSError, ValueError) as e:
            raise click.UsageError(f"cannot read {p}: {e}")
    try:
        diff = diff_profiles(docs[0], docs[1])
    except ValueError as e:
        raise click.UsageError(str(e))
    if as_json:
        click.echo(json.dumps(diff, indent=2))
        return
    dk = diff["dominant_kernel"]
    if dk is not None:
        click.echo(
            f"dominant kernel delta: {dk['family']} "
            f"{dk['device_ms_per_dispatch_a']} -> "
            f"{dk['device_ms_per_dispatch_b']} ms/dispatch"
            + (f" (x{dk['ratio']})" if dk.get("ratio") else "")
            + (f", {dk['bound_by']}-bound" if dk.get("bound_by") else ""))
    df = diff["dominant_frame"]
    if df is not None:
        click.echo(f"dominant host frame delta: {df['frame']} "
                   f"sample share {df['share_a']} -> {df['share_b']}")
    if "mfu_rolling_delta" in diff:
        click.echo(f"rolling MFU delta: {diff['mfu_rolling_delta']:+}")
    for row in diff["kernel_deltas"][:6]:
        click.echo(f"  kernel {row['family']}: "
                   f"{row['delta_ms_per_dispatch']:+} ms/dispatch",
                   err=True)
    for row in diff["frame_deltas"][:6]:
        click.echo(f"  frame {row['frame']}: {row['delta_share']:+} share",
                   err=True)


@cli.command()
def spawn_from_env():
    """Run ``spawn`` with arguments taken from PATHWAY_SPAWN_ARGS
    (reference cli.py:125 — the container entrypoint hook)."""
    args = os.environ.get("PATHWAY_SPAWN_ARGS")
    if args:
        cli.main(args=["spawn", *args.split(" ")],
                 prog_name="pathway-tpu", standalone_mode=True)


def main() -> None:
    cli.main(prog_name="pathway-tpu")


if __name__ == "__main__":
    main()
