"""``pathway-tpu`` command line interface
(reference: python/pathway/cli.py:53-280 — spawn / replay / spawn-from-env).

``spawn -t T -n N program.py`` forks N processes of the user program with
``PATHWAY_THREADS/PROCESSES/PROCESS_ID/FIRST_PORT/RUN_ID`` set — each
process hosts its shard of the device mesh (the reference's timely cluster
topology, re-aimed at multi-host TPU). ``replay`` re-runs a program against
a recorded snapshot directory with batch/speedrun timing, optionally
continuing live afterwards. Recording/replay wiring rides the persistence
env vars consumed by ``pw.run`` (internals/run.py)."""

from __future__ import annotations

import os
import subprocess
import sys
import uuid

import click

import pathway_tpu as pw


def _plural(n: int, singular: str, plural: str) -> str:
    return f"{n} {singular if n == 1 else plural}"


def spawn_program(*, threads: int, processes: int, first_port: int,
                  program: str, arguments: tuple[str, ...], env_base: dict):
    """Fork N processes of the user program, each owning T logical workers
    (reference: cli.py:53-110,166 — PATHWAY_THREADS/PROCESSES/PROCESS_ID/
    FIRST_PORT envs; processes cluster over TCP at FIRST_PORT+i,
    engine/multiproc.py)."""
    click.echo(
        f"Preparing {_plural(processes, 'process', 'processes')} "
        f"({_plural(processes * threads, 'total worker', 'total workers')})",
        err=True)
    run_id = str(uuid.uuid4())
    handles = []
    for pid in range(processes):
        env = dict(env_base)
        env["PATHWAY_THREADS"] = str(threads)
        env["PATHWAY_PROCESSES"] = str(processes)
        env["PATHWAY_FIRST_PORT"] = str(first_port)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        env["PATHWAY_RUN_ID"] = run_id
        handles.append(subprocess.Popen([program, *arguments], env=env))
    rc = 0
    try:
        for handle in handles:
            rc = handle.wait() or rc
    finally:
        for handle in handles:
            if handle.poll() is None:
                handle.terminate()
    sys.exit(rc)


@click.group()
@click.version_option(version=pw.__version__, prog_name="pathway-tpu")
def cli() -> None:
    pass


_spawn_opts = [
    click.option("-t", "--threads", metavar="N", type=int, default=1,
                 help="number of threads per process"),
    click.option("-n", "--processes", metavar="N", type=int, default=1,
                 help="number of processes"),
    click.option("--first-port", type=int, metavar="PORT", default=10000,
                 help="first port to use for communication"),
]


def _apply(opts, f):
    for opt in reversed(opts):
        f = opt(f)
    return f


@cli.command(context_settings={"allow_interspersed_args": False,
                               "show_default": True})
@click.option("--record", is_flag=True,
              help="record data from connectors while running")
@click.option("--record-path", type=str, default="record",
              help="directory in which recording is stored")
@click.argument("program")
@click.argument("arguments", nargs=-1)
@click.pass_context
def spawn(ctx, record, record_path, program, arguments,
          threads=1, processes=1, first_port=10000):
    env = os.environ.copy()
    if record:
        env["PATHWAY_REPLAY_STORAGE"] = record_path
        env["PATHWAY_SNAPSHOT_ACCESS"] = "record"
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    spawn_program(threads=threads, processes=processes,
                  first_port=first_port, program=program,
                  arguments=arguments, env_base=env)


spawn = _apply(_spawn_opts, spawn)


@cli.command(context_settings={"allow_interspersed_args": False,
                               "show_default": True})
@click.option("--record-path", type=str, default="record",
              help="directory in which recording is stored")
@click.option("--mode",
              type=click.Choice(["batch", "speedrun"], case_sensitive=False),
              help="mode of replaying data")
@click.option("--continue", "continue_after_replay", is_flag=True,
              help="continue with realtime data after the recording replays")
@click.argument("program")
@click.argument("arguments", nargs=-1)
def replay(record_path, mode, continue_after_replay, program, arguments,
           threads=1, processes=1, first_port=10000):
    env = os.environ.copy()
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_SNAPSHOT_ACCESS"] = "replay"
    if mode:
        env["PATHWAY_PERSISTENCE_MODE"] = (
            "batch" if mode.lower() == "batch" else "speedrun_replay")
    if continue_after_replay:
        env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "true"
    spawn_program(threads=threads, processes=processes,
                  first_port=first_port, program=program,
                  arguments=arguments, env_base=env)


replay = _apply(_spawn_opts, replay)


@cli.command()
def spawn_from_env():
    """Run ``spawn`` with arguments taken from PATHWAY_SPAWN_ARGS
    (reference cli.py:125 — the container entrypoint hook)."""
    args = os.environ.get("PATHWAY_SPAWN_ARGS")
    if args:
        cli.main(args=["spawn", *args.split(" ")],
                 prog_name="pathway-tpu", standalone_mode=True)


def main() -> None:
    cli.main(prog_name="pathway-tpu")


if __name__ == "__main__":
    main()
