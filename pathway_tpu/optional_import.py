"""Optional-dependency import guard (reference: pathway/optional_import.py
— same contract, pointing at this package's extras)."""

from contextlib import contextmanager


@contextmanager
def optional_imports(extra: str):
    try:
        yield
    except ImportError as e:
        raise ImportError(
            f"{e}. Consider installing 'pathway-tpu[{extra}]'") from e
