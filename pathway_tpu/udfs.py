"""Alias module (reference: pathway/udfs.py — a top-level import shim):
``import pathway_tpu.udfs`` resolves to the implementing module."""

import sys

from pathway_tpu.internals import udfs as _impl

sys.modules[__name__] = _impl
