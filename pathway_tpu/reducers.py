"""Alias module (reference: pathway/reducers.py — a top-level import shim):
``import pathway_tpu.reducers`` resolves to the implementing module."""

import sys

from pathway_tpu.internals import reducers_frontend as _impl

sys.modules[__name__] = _impl
