"""Alias module (reference: pathway/universes.py — a top-level import shim):
``import pathway_tpu.universes`` resolves to the implementing module."""

import sys

from pathway_tpu.internals import universes as _impl

sys.modules[__name__] = _impl
