"""pw.persistence — checkpoint/resume configuration
(reference: python/pathway/persistence/__init__.py:12,89 +
src/persistence/). Engine-side implementation: engine/persistence.py."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any


class Backend:
    kind = "mock"

    def __init__(self, kind: str, path: str | None = None, **kwargs):
        self.kind = kind
        self.path = path
        self.options = kwargs

    @classmethod
    def filesystem(cls, path: str) -> "Backend":
        return cls("filesystem", path)

    @classmethod
    def s3(cls, root_path: str, bucket_settings: Any = None) -> "Backend":
        """Snapshots as objects on S3-compatible storage through the
        native SigV4 client (io/s3/_client.py) — ``bucket_settings`` is a
        pw.io.s3.AwsS3Settings (endpoint/credentials); ``root_path`` is
        ``s3://bucket/prefix`` (reference: Backend.s3,
        persistence/__init__.py:49 + S3 metadata backend)."""
        return cls("s3", root_path, bucket_settings=bucket_settings)

    @classmethod
    def azure(cls, root_path: str, account: Any = None, **kw) -> "Backend":
        return cls("azure", root_path, account=account, **kw)

    @classmethod
    def mock(cls, events: Any = None) -> "Backend":
        return cls("mock")


class PersistenceMode(enum.Enum):
    """reference: src/connectors/mod.rs:107 / engine.pyi:776-787."""

    BATCH = "batch"
    SPEEDRUN_REPLAY = "speedrun_replay"
    REALTIME_REPLAY = "realtime_replay"
    PERSISTING = "persisting"
    SELECTIVE_PERSISTING = "selective_persisting"
    UDF_CACHING = "udf_caching"


@dataclass
class Config:
    backend: Backend | None = None
    snapshot_interval_ms: int = 0
    persistence_mode: PersistenceMode = PersistenceMode.PERSISTING
    continue_after_replay: bool = True
    # operator-state snapshot cadence (engine/persistence.py): every N
    # commit ticks, and/or whenever the WAL grew by >= N bytes since the
    # last snapshot. 0/None disables (WAL-only recovery: restart cost
    # grows with stream age). Env overrides: PATHWAY_SNAPSHOT_EVERY_TICKS
    # / PATHWAY_SNAPSHOT_EVERY_BYTES.
    snapshot_every_ticks: int | None = None
    snapshot_every_bytes: int | None = None

    @classmethod
    def simple_config(cls, backend: Backend, **kwargs) -> "Config":
        return cls(backend=backend, **kwargs)

    def __post_init__(self):
        if isinstance(self.persistence_mode, str):
            self.persistence_mode = PersistenceMode(self.persistence_mode)
