"""pw.demo — synthetic streams (reference: python/pathway/demo/__init__.py:28-258).

Streams are generated as timed diff-feeds (speedrun semantics): each value
arrives at its own logical timestamp, exercising the incremental path of
every operator downstream, without wall-clock waits.
"""

from __future__ import annotations

import csv as _csv
from typing import Any, Callable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.keys import hash_values
from pathway_tpu.internals.table import Plan, Table
from pathway_tpu.internals.universe import Universe


def generate_custom_stream(value_generators: dict[str, Callable[[int], Any]],
                           *, schema: type[sch.Schema] | None = None,
                           nb_rows: int | None = 100,
                           autocommit_duration_ms: int = 1000,
                           input_rate: float = 1.0,
                           persistent_id=None, name=None) -> Table:
    n = nb_rows if nb_rows is not None else 100
    names = list(value_generators.keys())
    if schema is None:
        schema = sch.schema_from_types(**{c: dt.ANY for c in names})
    col_order = schema.column_names()
    keys, rows, times = [], [], []
    for i in range(n):
        values = {c: value_generators[c](i) for c in names}
        keys.append(hash_values("demo", i))
        rows.append(tuple(values.get(c) for c in col_order))
        times.append(i + 1)
    plan = Plan("static", keys=keys, rows=rows, times=times, diffs=None)
    return Table(plan, schema, Universe(), name=name or "demo_stream")


def range_stream(*, nb_rows: int = 30, offset: int = 0,
                 autocommit_duration_ms: int = 1000,
                 input_rate: float = 1.0, name=None) -> Table:
    return generate_custom_stream(
        {"value": lambda i: i + offset},
        schema=sch.schema_from_types(value=dt.INT),
        nb_rows=nb_rows, name=name or "range_stream")


def noisy_linear_stream(*, nb_rows: int = 10, input_rate: float = 1.0,
                        name=None) -> Table:
    import random

    rng = random.Random(0)
    return generate_custom_stream(
        {"x": lambda i: float(i),
         "y": lambda i: float(i) + rng.uniform(-1, 1)},
        schema=sch.schema_from_types(x=dt.FLOAT, y=dt.FLOAT),
        nb_rows=nb_rows, name=name or "noisy_linear")


def replay_csv(path: str, *, schema: type[sch.Schema],
               input_rate: float = 1.0, name=None) -> Table:
    col_order = schema.column_names()
    dtypes = schema._dtypes()
    keys, rows, times = [], [], []
    with open(path, newline="") as f:
        for i, rec in enumerate(_csv.DictReader(f)):
            vals = {c: _coerce(rec.get(c), dtypes[c]) for c in col_order}
            keys.append(hash_values("replay", path, i))
            rows.append(tuple(vals[c] for c in col_order))
            times.append(i + 1)
    plan = Plan("static", keys=keys, rows=rows, times=times, diffs=None)
    return Table(plan, schema, Universe(), name=name or "replay_csv")


def replay_csv_with_time(path: str, *, schema: type[sch.Schema],
                         time_column: str, unit: str = "s",
                         autocommit_ms: int = 100, speedup: float = 1.0,
                         name=None) -> Table:
    col_order = schema.column_names()
    dtypes = schema._dtypes()
    entries = []
    with open(path, newline="") as f:
        for i, rec in enumerate(_csv.DictReader(f)):
            vals = {c: _coerce(rec.get(c), dtypes[c]) for c in col_order}
            t = vals.get(time_column)
            entries.append((t, i, vals))
    entries.sort(key=lambda e: (e[0], e[1]))
    keys, rows, times = [], [], []
    for t, i, vals in entries:
        keys.append(hash_values("replay", path, i))
        rows.append(tuple(vals[c] for c in col_order))
        times.append(int(t) if t is not None else i)
    plan = Plan("static", keys=keys, rows=rows, times=times, diffs=None)
    return Table(plan, schema, Universe(), name=name or "replay_csv_time")


def _coerce(v, d):
    if v is None:
        return None
    base = dt.unoptionalize(d)
    try:
        if base is dt.INT:
            return int(v)
        if base is dt.FLOAT:
            return float(v)
        if base is dt.BOOL:
            return str(v).lower() in ("1", "true", "yes", "on")
    except ValueError:
        return None
    return v
