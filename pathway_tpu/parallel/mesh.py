"""Mesh construction + sharding helpers.

Replaces the reference's worker/cluster configuration
(src/engine/dataflow/config.rs:88-127: PATHWAY_THREADS × PATHWAY_PROCESSES →
timely thread/TCP topology). Here the topology is a `jax.sharding.Mesh`
over TPU chips: the ``data`` axis carries keyspace/batch shards (what the
reference calls workers) and the ``model`` axis carries tensor-parallel
weight shards. Env vars:

- ``PATHWAY_DATA_PARALLEL``  — size of the data axis (default: all devices)
- ``PATHWAY_MODEL_PARALLEL`` — size of the model axis (default 1)

There is deliberately no 8-worker cap (the reference's free-tier
MAX_WORKERS, config.rs:7, is a license artifact, not a design point).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass

DATA_AXIS = "data"
MODEL_AXIS = "model"


def validate_shard_specs(mesh, in_specs, out_specs) -> None:
    """Raise a clear ValueError when a PartitionSpec names an axis the mesh
    does not have — otherwise the typo surfaces as an opaque error deep in
    jax's shard_map lowering. (The static counterpart — rank consistency
    against plan-propagated operand shapes — is PWT103 in
    internals/static_check/shard_check.py.)"""
    axes = set(getattr(mesh, "axis_names", ()))
    if not axes:
        return

    def walk(spec):
        if spec is None:
            return
        # PartitionSpec may or may not subclass tuple depending on the jax
        # version, so detect it by mro name before treating tuples as
        # containers of further specs
        if any(c.__name__ == "PartitionSpec" for c in type(spec).__mro__):
            for entry in spec:  # iterates the per-dim entries
                names = entry if isinstance(entry, tuple) else (entry,)
                for a in names:
                    if a is not None and a not in axes:
                        raise ValueError(
                            f"shard_map spec names axis {a!r} but the mesh "
                            f"only has axes {sorted(axes)} (PWT103)")
            return
        if isinstance(spec, (list, tuple)):
            for s in spec:
                walk(s)

    walk(in_specs)
    walk(out_specs)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: newer jax exposes it at the
    top level with ``check_vma``; older releases only have
    ``jax.experimental.shard_map.shard_map`` with the same flag spelled
    ``check_rep``."""
    import jax

    validate_shard_specs(mesh, in_specs, out_specs)
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


@dataclass(frozen=True)
class MeshConfig:
    data: int
    model: int = 1

    def validate(self, n_devices: int) -> list[str]:
        """Reasons this topology cannot tile ``n_devices`` chips (empty =
        fine). Shared by :meth:`from_env` and the static shard checker
        (PWT101) so eager and pre-execution validation agree."""
        problems = []
        if self.data < 1 or self.model < 1:
            problems.append(
                f"axis sizes must be positive, got data={self.data}, "
                f"model={self.model}")
            return problems
        n = self.data * self.model
        if n > n_devices:
            problems.append(
                f"mesh {self.data}x{self.model} needs {n} devices but "
                f"only {n_devices} are available")
        elif n_devices % n != 0:
            problems.append(
                f"mesh {self.data}x{self.model} covers {n} of {n_devices} "
                f"devices and {n} does not divide {n_devices} — "
                f"{n_devices - n} chips would sit idle")
        return problems

    @staticmethod
    def from_env(n_devices: int | None = None) -> "MeshConfig":
        import jax

        if n_devices is None:
            n_devices = len(jax.devices())
        model_env = os.environ.get("PATHWAY_MODEL_PARALLEL")
        data_env = os.environ.get("PATHWAY_DATA_PARALLEL")
        try:
            model = int(model_env) if model_env is not None else 1
            data = (int(data_env) if data_env is not None
                    else max(1, n_devices // model))
        except ValueError:
            raise ValueError(
                f"PATHWAY_DATA_PARALLEL={data_env!r} / "
                f"PATHWAY_MODEL_PARALLEL={model_env!r} must be positive "
                f"integers") from None
        config = MeshConfig(data=data, model=model)
        # validate eagerly: letting jax discover the mismatch later fails
        # deep in mesh construction with an opaque reshape error that
        # never names the env vars that caused it
        problems = config.validate(n_devices)
        if problems:
            raise ValueError(
                f"invalid mesh topology from environment "
                f"(PATHWAY_DATA_PARALLEL={data_env!r}, "
                f"PATHWAY_MODEL_PARALLEL={model_env!r}, {n_devices} "
                f"devices visible): " + "; ".join(problems))
        return config


def make_mesh(config: MeshConfig | None = None, *, devices=None):
    """Build a 2-D (data, model) Mesh over the given (or all) devices."""
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig.from_env(len(devices))
    n = config.data * config.model
    if n > len(devices):
        raise ValueError(
            f"mesh {config} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(config.data, config.model)
    return jax.sharding.Mesh(arr, (DATA_AXIS, MODEL_AXIS))


_ACTIVE_MESH = None


def get_mesh():
    """The process-wide active mesh, creating a default one on first use."""
    global _ACTIVE_MESH
    if _ACTIVE_MESH is None:
        _ACTIVE_MESH = make_mesh()
    return _ACTIVE_MESH


def current_mesh():
    """The active mesh or None (never creates one)."""
    return _ACTIVE_MESH


@contextlib.contextmanager
def use_mesh(mesh):
    """Set the process-wide mesh for the duration of the block."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def shard_batch(mesh=None, *extra_axes):
    """NamedSharding placing dim 0 on the data axis, rest replicated."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    spec = jax.sharding.PartitionSpec(DATA_AXIS, *extra_axes)
    return jax.sharding.NamedSharding(mesh, spec)


def replicated(mesh=None):
    import jax

    if mesh is None:
        mesh = get_mesh()
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
