"""Mesh construction + sharding helpers.

Replaces the reference's worker/cluster configuration
(src/engine/dataflow/config.rs:88-127: PATHWAY_THREADS × PATHWAY_PROCESSES →
timely thread/TCP topology). Here the topology is a `jax.sharding.Mesh`
over TPU chips: the ``data`` axis carries keyspace/batch shards (what the
reference calls workers) and the ``model`` axis carries tensor-parallel
weight shards. Env vars:

- ``PATHWAY_DATA_PARALLEL``  — size of the data axis (default: all devices)
- ``PATHWAY_MODEL_PARALLEL`` — size of the model axis (default 1)

There is deliberately no 8-worker cap (the reference's free-tier
MAX_WORKERS, config.rs:7, is a license artifact, not a design point).
"""

from __future__ import annotations

import contextlib
import os
from dataclasses import dataclass

DATA_AXIS = "data"
MODEL_AXIS = "model"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: newer jax exposes it at the
    top level with ``check_vma``; older releases only have
    ``jax.experimental.shard_map.shard_map`` with the same flag spelled
    ``check_rep``."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


@dataclass(frozen=True)
class MeshConfig:
    data: int
    model: int = 1

    @staticmethod
    def from_env(n_devices: int | None = None) -> "MeshConfig":
        import jax

        if n_devices is None:
            n_devices = len(jax.devices())
        model = int(os.environ.get("PATHWAY_MODEL_PARALLEL", "1"))
        data_env = os.environ.get("PATHWAY_DATA_PARALLEL")
        if data_env is not None:
            data = int(data_env)
        else:
            data = max(1, n_devices // model)
        return MeshConfig(data=data, model=model)


def make_mesh(config: MeshConfig | None = None, *, devices=None):
    """Build a 2-D (data, model) Mesh over the given (or all) devices."""
    import jax
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig.from_env(len(devices))
    n = config.data * config.model
    if n > len(devices):
        raise ValueError(
            f"mesh {config} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(config.data, config.model)
    return jax.sharding.Mesh(arr, (DATA_AXIS, MODEL_AXIS))


_ACTIVE_MESH = None


def get_mesh():
    """The process-wide active mesh, creating a default one on first use."""
    global _ACTIVE_MESH
    if _ACTIVE_MESH is None:
        _ACTIVE_MESH = make_mesh()
    return _ACTIVE_MESH


def current_mesh():
    """The active mesh or None (never creates one)."""
    return _ACTIVE_MESH


@contextlib.contextmanager
def use_mesh(mesh):
    """Set the process-wide mesh for the duration of the block."""
    global _ACTIVE_MESH
    prev = _ACTIVE_MESH
    _ACTIVE_MESH = mesh
    try:
        yield mesh
    finally:
        _ACTIVE_MESH = prev


def shard_batch(mesh=None, *extra_axes):
    """NamedSharding placing dim 0 on the data axis, rest replicated."""
    import jax

    if mesh is None:
        mesh = get_mesh()
    spec = jax.sharding.PartitionSpec(DATA_AXIS, *extra_axes)
    return jax.sharding.NamedSharding(mesh, spec)


def replicated(mesh=None):
    import jax

    if mesh is None:
        mesh = get_mesh()
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
