"""Multi-chip sharded brute-force KNN.

Pod-scale variant of ops/knn.py (reference: BruteForceKNNIndex,
src/external_integration/brute_force_knn_integration.rs:22,187-229 — which
is per-worker: each timely worker owns the rows routed to it by key shard).
Here the vector slab is one logical array of shape
``(n_shards, cap_per_shard, dim)`` sharded over the mesh ``data`` axis:
each chip scores queries against its local shard (one MXU matmul), takes a
local top-k, and the per-shard candidates are merged with a second top-k —
the cross-chip traffic is only ``n_shards × B × k`` scores over ICI, never
the slab itself. This is the distributed-KNN design for BASELINE.md
config 5 (multi-worker KNN over a stream).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.keys import Pointer
from pathway_tpu.ops.knn import KnnMetric, _quantize_i8_np, _round_up
from pathway_tpu.ops.knn import passes_filter as _passes
from pathway_tpu.parallel.mesh import DATA_AXIS, get_mesh
from pathway_tpu.parallel.mesh import shard_map as _shard_map


def slab_cap_per_shard(n_shards: int, reserved_space: int,
                       page_rows: int | None = None) -> int:
    """Per-shard slab capacity for a reservation of ``reserved_space`` rows.

    The ONE place the slab layout is decided: the index constructor sizes
    its storage with it and the static shard checker
    (internals/static_check/shard_check.py, PWT102) predicts padding/skew
    from it — the two can never disagree about what a reservation costs.
    Under the paged store (``page_rows`` set) each shard's slab is a whole
    number of pages, so the per-shard capacity rounds up to the page size.
    """
    per = max(reserved_space // n_shards + 1, 1)
    per = max(128, _round_up(per, 128))
    if page_rows:
        per = _round_up(per, page_rows)
    return per


def pages_per_shard(n_shards: int, reserved_space: int,
                    page_rows: int) -> int:
    """What a reservation costs in PAGES per shard — the paged-store unit
    the allocator and the static checker (PWT111) both reason in."""
    return slab_cap_per_shard(n_shards, reserved_space,
                              page_rows) // page_rows


def search_operand_layout(dtype: str) -> tuple[tuple[tuple, int], ...]:
    """``((sharded_axes, rank), ...)`` per search-kernel operand, in call
    order: queries, vectors, valid (+ scales, vsq for int8). ``sharded_axes``
    is a tuple of mesh axis names, one per leading operand dim (empty =
    replicated) — the symbolic twin of the ``in_specs`` handed to
    ``shard_map``. Shared by ``_get_search_fn`` and the static shard checker
    (PWT103), so the spec/rank contract is asserted against the layout the
    kernel actually uses."""
    base = (
        ((), 2),            # queries (B, D): replicated
        ((DATA_AXIS,), 3),  # vectors (S, C, D): slab dim over the data axis
        ((DATA_AXIS,), 2),  # valid (S, C)
    )
    if dtype == "int8":
        base = base + (
            ((DATA_AXIS,), 2),  # scales (S, C)
            ((DATA_AXIS,), 2),  # vsq (S, C)
        )
    return base


class ShardedKnnIndex:
    """Exact KNN over a mesh-sharded vector slab.

    Slots form one logical space of size ``n_shards * cap_per_shard``;
    slot ``s`` lives on shard ``s // cap_per_shard``. Adds are balanced by
    always allocating from the emptiest shard (the reference balances by
    key-hash routing, src/engine/dataflow/shard.rs:6-20; explicit balancing
    avoids hash skew in the slab).
    """

    device_bound = True  # pipeline through the device bridge (graph.py)

    def __new__(cls, *args, **kwargs):
        # paged per-shard storage is the default (PATHWAY_PAGED_STORE=0 /
        # paged=False keeps this contiguous per-shard slab class)
        if cls is ShardedKnnIndex:
            from pathway_tpu.engine.paged_store import paged_store_enabled

            if paged_store_enabled(kwargs.get("paged")):
                cls = PagedShardedKnnIndex
        return object.__new__(cls)

    def __init__(self, dimensions: int, *, mesh=None,
                 reserved_space: int = 0,
                 metric: KnnMetric | str = KnnMetric.L2SQ,
                 dtype: str = "float32", paged: bool | None = None,
                 page_rows: int | None = None, tenant: Any = None,
                 tenant_quotas: dict[Any, int] | None = None):
        if isinstance(metric, str):
            metric = KnnMetric(metric)
        if dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(f"unsupported sharded knn dtype {dtype!r} "
                             "(use 'float32', 'bfloat16' or 'int8')")
        self.dim = int(dimensions)
        self.metric = metric
        # per-shard slab storage: bf16 halves slab bytes/scan time per
        # chip, int8 halves them again (host-side per-row quantization at
        # flush, same scheme as ops/knn.py _quantize_i8; the host mirror
        # stays exact f32)
        self.dtype = dtype
        self._mesh = mesh if mesh is not None else get_mesh()
        self.n_shards = int(self._mesh.shape[DATA_AXIS])
        from pathway_tpu.engine.locking import create_rlock

        self._lock = create_rlock("ShardedKnnIndex._lock")
        self._key_to_slot: dict[Pointer, int] = {}
        self._slot_to_key: dict[int, Pointer] = {}
        self._filter_data: dict[Pointer, Any] = {}
        self._dirty: set[int] = set()
        self._search_fn_cache: dict[tuple, Callable] = {}
        self._init_storage(reserved_space, page_rows=page_rows,
                           tenant=tenant, tenant_quotas=tenant_quotas)

    def _init_storage(self, reserved_space: int, *,
                      page_rows: int | None = None, tenant: Any = None,
                      tenant_quotas: dict[Any, int] | None = None) -> None:
        if tenant_quotas:
            # quota accounting lives in the page allocator — the
            # contiguous per-shard slab has none. Loud, not silent: a
            # quota the runtime will not enforce is a security config bug
            import logging

            logging.getLogger("pathway_tpu.paged_store").warning(
                "tenant_quotas are only enforced by the paged store — "
                "the contiguous sharded slab (PATHWAY_PAGED_STORE=0) "
                "ignores them")
        self.cap_per_shard = slab_cap_per_shard(self.n_shards,
                                                reserved_space)
        cap = self.total_capacity
        self._host_vectors = np.zeros((cap, self.dim), dtype=np.float32)
        self._host_valid = np.zeros((cap,), dtype=bool)
        # per-shard LIFO free lists
        self._free: list[list[int]] = [
            list(range((s + 1) * self.cap_per_shard - 1,
                       s * self.cap_per_shard - 1, -1))
            for s in range(self.n_shards)
        ]
        self._dev_vectors = None
        self._dev_valid = None
        self._dev_scales = None  # int8 only: per-row scale + INT-domain
        self._dev_vsq = None     # squared norm, both (S, C) f32

    @property
    def total_capacity(self) -> int:
        return self.n_shards * self.cap_per_shard

    def __len__(self) -> int:
        return len(self._key_to_slot)

    # -- storage hooks (the paged subclass swaps these) -----------------
    def _ensure_free(self, n: int) -> None:
        while sum(len(f) for f in self._free) < n:
            self._grow()

    def _release_slot(self, slot: int) -> None:
        self._free[slot // self.cap_per_shard].append(slot)

    # ------------------------------------------------------------------
    def _alloc_slot(self, key: Pointer) -> int:
        """Slot for ``key``, allocating from the emptiest shard (growing if
        all shards are full). Lock held. Balances instead of key-hash routing
        (reference routes by hash, src/engine/dataflow/shard.rs:6-20) to
        avoid hash skew in the slab."""
        slot = self._key_to_slot.get(key)
        if slot is None:
            shard = max(range(self.n_shards),
                        key=lambda s: len(self._free[s]))
            if not self._free[shard]:
                self._grow()
                shard = max(range(self.n_shards),
                            key=lambda s: len(self._free[s]))
            slot = self._free[shard].pop()
            self._key_to_slot[key] = slot
            self._slot_to_key[slot] = key
        return slot

    def add(self, key: Pointer, vector: Any,
            filter_data: Any | None = None) -> None:
        with self._lock:
            vec = np.asarray(vector, dtype=np.float32).reshape(-1)
            if vec.shape[0] != self.dim:
                raise ValueError(
                    f"vector dim {vec.shape[0]} != index dim {self.dim}")
            slot = self._alloc_slot(key)
            self._host_vectors[slot] = vec
            self._host_valid[slot] = True
            if filter_data is not None:
                self._filter_data[key] = filter_data
            self._dirty.add(slot)

    def add_batch(self, keys: list[Pointer], vectors,
                  filter_data: list[Any] | None = None) -> None:
        """Vectorized add (same contract as ops.knn add_batch); rows go to
        the emptiest shards. Capacity is ensured up front because _grow()
        remaps slot ids — no grow may happen mid-allocation."""
        if len(keys) == 0:
            return
        vecs = np.asarray(vectors, dtype=np.float32)
        if vecs.ndim != 2 or vecs.shape[1] != self.dim:
            raise ValueError(
                f"expected ({len(keys)}, {self.dim}) vectors, got {vecs.shape}")
        if vecs.shape[0] != len(keys):
            raise ValueError(
                f"{len(keys)} keys but {vecs.shape[0]} vectors")
        if filter_data is not None and len(filter_data) != len(keys):
            raise ValueError(
                f"{len(keys)} keys but {len(filter_data)} filter_data entries")
        with self._lock:
            n_new = len({k for k in keys if k not in self._key_to_slot})
            self._ensure_free(n_new)
            slots = np.empty(len(keys), dtype=np.int64)
            for i, key in enumerate(keys):
                slots[i] = self._alloc_slot(key)
                if filter_data is not None and filter_data[i] is not None:
                    self._filter_data[key] = filter_data[i]
            self._host_vectors[slots] = vecs
            self._host_valid[slots] = True
            self._dirty.update(slots.tolist())

    def remove(self, key: Pointer) -> None:
        with self._lock:
            slot = self._key_to_slot.pop(key, None)
            if slot is None:
                return
            del self._slot_to_key[slot]
            self._filter_data.pop(key, None)
            self._host_valid[slot] = False
            self._release_slot(slot)
            self._dirty.add(slot)

    def _grow(self) -> None:
        """Double per-shard capacity; slot ids are remapped shard-locally."""
        old_per = self.cap_per_shard
        new_per = old_per * 2
        cap = self.n_shards * new_per
        new_vec = np.zeros((cap, self.dim), dtype=np.float32)
        new_valid = np.zeros((cap,), dtype=bool)
        remap: dict[int, int] = {}
        for s in range(self.n_shards):
            old_lo, new_lo = s * old_per, s * new_per
            new_vec[new_lo:new_lo + old_per] = \
                self._host_vectors[old_lo:old_lo + old_per]
            new_valid[new_lo:new_lo + old_per] = \
                self._host_valid[old_lo:old_lo + old_per]
            for i in range(old_per):
                remap[old_lo + i] = new_lo + i
        self._host_vectors = new_vec
        self._host_valid = new_valid
        self._key_to_slot = {k: remap[v] for k, v in self._key_to_slot.items()}
        self._slot_to_key = {remap[s]: k for s, k in self._slot_to_key.items()}
        self._free = [
            [remap[s] for s in shard_free] +
            list(range((i + 1) * new_per - 1, i * new_per + old_per - 1, -1))
            for i, shard_free in enumerate(self._free)
        ]
        self.cap_per_shard = new_per
        self._dev_vectors = None
        self._dev_valid = None
        self._dev_scales = None
        self._dev_vsq = None
        self._search_fn_cache.clear()
        self._dirty.clear()

    # ------------------------------------------------------------------
    def flush_device(self) -> None:
        """Push pending host-mirror changes to the sharded device slab now
        (same contract as ops.knn.BruteForceKnnIndex.flush_device — the
        external-index operator calls this after ingest-only ticks so
        uploads ride the device leg instead of the next query)."""
        with self._lock:
            self._flush_to_device()

    def _flush_to_device(self):
        import jax
        import jax.numpy as jnp

        S, C, D = self.n_shards, self.cap_per_shard, self.dim
        sharding = jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(DATA_AXIS))

        def slab_rows(rows):
            if self.dtype == "bfloat16":
                return rows.astype(jnp.bfloat16) if hasattr(rows, "astype") \
                    else rows
            return rows

        if self._dev_vectors is None:
            if self.dtype == "int8":
                q, scale, vsq = _quantize_i8_np(self._host_vectors)
                self._dev_vectors = jax.device_put(
                    q.reshape(S, C, D), sharding)
                self._dev_scales = jax.device_put(
                    scale.reshape(S, C), sharding)
                self._dev_vsq = jax.device_put(
                    vsq.reshape(S, C), sharding)
            else:
                host = self._host_vectors
                if self.dtype == "bfloat16":
                    import ml_dtypes

                    host = host.astype(ml_dtypes.bfloat16)
                self._dev_vectors = jax.device_put(
                    host.reshape(S, C, D), sharding)
            self._dev_valid = jax.device_put(
                self._host_valid.reshape(S, C), sharding)
            self._dirty.clear()
            return
        if self._dirty:
            idxs = np.fromiter(self._dirty, dtype=np.int32)
            self._dirty.clear()
            sh, sl = idxs // C, idxs % C
            if self.dtype == "int8":
                q, scale, vsq = _quantize_i8_np(self._host_vectors[idxs])
                self._dev_vectors = self._dev_vectors.at[sh, sl].set(
                    jnp.asarray(q))
                self._dev_scales = self._dev_scales.at[sh, sl].set(
                    jnp.asarray(scale))
                self._dev_vsq = self._dev_vsq.at[sh, sl].set(
                    jnp.asarray(vsq))
            else:
                self._dev_vectors = self._dev_vectors.at[sh, sl].set(
                    slab_rows(jnp.asarray(self._host_vectors[idxs])))
            self._dev_valid = self._dev_valid.at[sh, sl].set(
                jnp.asarray(self._host_valid[idxs]))

    @staticmethod
    def _local_scores(queries, vecs, valid_row, extras, metric, int8):
        """(B, C) scores of replicated queries vs one shard-local slab
        block — the ONE scoring block both the contiguous and the paged
        (multi-extent) sharded kernels trace, so their per-row arithmetic
        can never diverge."""
        import jax
        import jax.numpy as jnp

        if int8:
            scales, vsq = extras
            vs = vecs.astype(jnp.bfloat16)
            if metric == KnnMetric.COS:
                qn = queries / (jnp.linalg.norm(
                    queries, axis=1, keepdims=True) + 1e-12)
                dots = jax.lax.dot_general(
                    qn.astype(jnp.bfloat16), vs,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                # per-row scale cancels for cosine (see ops/knn.py)
                scores = dots * jax.lax.rsqrt(vsq + 1e-12)[None, :]
            else:
                dots = jax.lax.dot_general(
                    queries.astype(jnp.bfloat16), vs,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                scores = (2.0 * dots * scales[None, :]
                          - vsq * (scales * scales)[None, :])
        elif metric == KnnMetric.COS:
            qn = queries / (jnp.linalg.norm(queries, axis=1,
                                            keepdims=True) + 1e-12)
            vn = vecs / (jnp.linalg.norm(
                vecs.astype(jnp.float32), axis=1, keepdims=True) + 1e-12)
            scores = qn @ vn.T
        else:
            dots = queries @ vecs.T
            vf = vecs.astype(jnp.float32)
            v_sq = jnp.sum(vf * vf, axis=1)
            scores = 2.0 * dots - v_sq[None, :]
        return jnp.where(valid_row[None, :], scores, -jnp.inf)

    def _get_search_fn(self, k: int):
        cache_key = (k, self.cap_per_shard, self.dtype)
        fn = self._search_fn_cache.get(cache_key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        metric = self.metric
        C = self.cap_per_shard
        int8 = self.dtype == "int8"
        score = self._local_scores

        def local_search(queries, vectors, valid, *extras):
            # queries (B, D) replicated; vectors (1, C, D), valid (1, C)
            # local; extras = (scales, vsq) per-shard for int8
            ex = (extras[0][0], extras[1][0]) if int8 else ()
            scores = score(queries, vectors[0], valid[0], ex, metric, int8)
            s, i = jax.lax.top_k(scores, min(k, C))  # (B, k) local
            # globalize slot ids with this shard's offset
            shard_id = jax.lax.axis_index(DATA_AXIS)
            gi = i + shard_id * C
            # gather candidates from every shard: (S, B, k) on each chip
            all_s = jax.lax.all_gather(s, DATA_AXIS)
            all_i = jax.lax.all_gather(gi, DATA_AXIS)
            B = queries.shape[0]
            cand_s = jnp.transpose(all_s, (1, 0, 2)).reshape(B, -1)
            cand_i = jnp.transpose(all_i, (1, 0, 2)).reshape(B, -1)
            ms, mpos = jax.lax.top_k(cand_s, min(k, cand_s.shape[1]))
            mi = jnp.take_along_axis(cand_i, mpos, axis=1)
            return ms, mi

        in_specs = tuple(P(*axes)
                         for axes, _rank in search_operand_layout(self.dtype))
        shard_fn = _shard_map(
            local_search, mesh=self._mesh,
            in_specs=in_specs,
            out_specs=(P(), P()),
            check_vma=False,
        )
        fn = jax.jit(shard_fn)
        self._search_fn_cache[cache_key] = fn
        return fn

    def _device_topk(self, qmat, fetch_k: int):
        """(scores, global slots) host arrays, best first. Lock held,
        device state flushed."""
        search_fn = self._get_search_fn(fetch_k)
        extras = ((self._dev_scales, self._dev_vsq)
                  if self.dtype == "int8" else ())
        ts, ti = search_fn(qmat, self._dev_vectors, self._dev_valid,
                           *extras)
        return np.asarray(ts), np.asarray(ti)

    def search(self, queries: list[tuple]) -> list[tuple]:
        """Same contract as ops.knn.BruteForceKnnIndex.search."""
        if not queries:
            return []
        with self._lock:
            if not self._key_to_slot:
                return [() for _ in queries]
            self._flush_to_device()

            max_k = max(int(q[2] or 3) for q in queries)
            has_filter = any(q[3] is not None for q in queries)
            fetch_k = max(1, min(self.cap_per_shard,
                                 max_k * 4 if has_filter else max_k))
            qmat = np.stack([np.asarray(q[1], dtype=np.float32).reshape(-1)
                             for q in queries])
            top_scores, top_idx = self._device_topk(qmat, fetch_k)

            out = []
            for qi, (qkey, qvec, limit, filt) in enumerate(queries):
                limit = int(limit or 3)
                matches = []
                qnorm_sq = None
                for rank in range(top_scores.shape[1]):
                    score = top_scores[qi, rank]
                    if not math.isfinite(score):
                        break
                    key = self._slot_to_key.get(int(top_idx[qi, rank]))
                    if key is None:
                        continue
                    if filt is not None and not self._passes_filter(key, filt):
                        continue
                    if self.metric == KnnMetric.COS:
                        dist = 1.0 - float(score)
                    else:
                        if qnorm_sq is None:
                            q = np.asarray(qvec, dtype=np.float32).reshape(-1)
                            qnorm_sq = float(q @ q)
                        dist = max(0.0, qnorm_sq - float(score))
                    matches.append((key, dist))
                    if len(matches) >= limit:
                        break
                out.append(tuple(matches))
            return out

    def _passes_filter(self, key: Pointer, filt: Any) -> bool:
        return _passes(self._filter_data, key, filt)


class _ShardExtent:
    """One sharded device allocation: ``cap_per_shard`` rows PER SHARD,
    laid out as (n_shards, cap_per_shard, dim) over the mesh data axis.
    Global slots [base + s*cap, base + (s+1)*cap) belong to shard s."""

    __slots__ = ("base", "cap_per_shard", "vectors", "valid", "scales",
                 "vsq")

    def __init__(self, base: int, cap_per_shard: int):
        self.base = base
        self.cap_per_shard = cap_per_shard
        self.vectors = None
        self.valid = None
        self.scales = None
        self.vsq = None


class PagedShardedKnnIndex(ShardedKnnIndex):
    """ShardedKnnIndex over per-shard page tables (the default —
    ``ShardedKnnIndex(...)`` constructs this class unless
    ``PATHWAY_PAGED_STORE=0`` / ``paged=False``).

    Each shard's slab is a whole number of pages (``slab_cap_per_shard``
    page-aligned ⇒ ``pages_per_shard`` is the reservation unit) tracked by
    ONE PageAllocator whose regions are (extent, shard) blocks. Growth
    appends a sharded extent — a fresh (S, C_new, D) device allocation —
    with NO slot remapping and NO re-upload of existing extents (the
    contiguous path remaps every slot and re-uploads the whole slab).
    The search kernel scores every extent shard-locally, merges the
    per-extent top-k on-chip, and only then pays the cross-chip
    all-gather: ICI traffic stays n_shards x B x k scores regardless of
    extent count."""

    def _init_storage(self, reserved_space: int, *,
                      page_rows: int | None = None, tenant: Any = None,
                      tenant_quotas: dict[Any, int] | None = None) -> None:
        from pathway_tpu.engine.paged_store import (PageAllocator,
                                                    quota_pages)
        from pathway_tpu.engine.paged_store import page_rows as _page_rows

        self._page_rows = _page_rows(page_rows)
        self._tenant = tenant
        quota_p = ({t: quota_pages(rows, self._page_rows)
                    for t, rows in tenant_quotas.items()}
                   if tenant_quotas else None)
        self.cap_per_shard = 0  # grows as extents are added
        self._extents: list[_ShardExtent] = []
        self._allocator = PageAllocator(self._page_rows, quota_p)
        # per-shard free-row counters: the emptiest-shard choice runs per
        # KEY on bulk ingest, and a full allocator scan there is O(S*E)
        # dict work per row. The counters are exact without quotas; with
        # quotas the allocator scan stays authoritative (quota headroom is
        # global, a raw counter could overstate a shard's availability)
        self._shard_free_rows = [0] * self.n_shards
        self.grow_events = 0
        self._host_vectors = np.zeros((0, self.dim), dtype=np.float32)
        self._host_valid = np.zeros((0,), dtype=bool)
        self._free = None  # slot accounting lives in the page allocator
        self._dev_vectors = None   # unused in paged mode (per-extent state)
        self._dev_valid = None
        self._dev_scales = None
        self._dev_vsq = None
        self._add_extent(slab_cap_per_shard(
            self.n_shards, reserved_space, self._page_rows))
        from pathway_tpu.engine.paged_store import register_pool

        register_pool(self)

    def stats(self) -> dict:
        """Pool-stats shape for engine.paged_store.live_paged_stats."""
        return self.page_stats()

    # -- extents ---------------------------------------------------------
    def _add_extent(self, cap_per_shard: int) -> None:
        base = self.total_capacity
        ext = _ShardExtent(base, cap_per_shard)
        eidx = len(self._extents)
        self._extents.append(ext)
        for s in range(self.n_shards):
            self._allocator.add_region(
                (eidx, s), base + s * cap_per_shard,
                cap_per_shard // self._page_rows)
            self._shard_free_rows[s] += cap_per_shard
        self.cap_per_shard += cap_per_shard
        cap = self.total_capacity
        new_vec = np.zeros((cap, self.dim), dtype=np.float32)
        new_vec[:len(self._host_vectors)] = self._host_vectors
        self._host_vectors = new_vec
        new_valid = np.zeros((cap,), dtype=bool)
        new_valid[:len(self._host_valid)] = self._host_valid
        self._host_valid = new_valid

    def _grow(self) -> None:
        """Online growth: one more sharded extent (per-shard size doubles
        the per-shard total so far) — existing extents, slot ids and the
        dirty set are untouched."""
        self.grow_events += 1
        self._add_extent(_round_up(self.cap_per_shard, self._page_rows))

    def page_stats(self) -> dict:
        with self._lock:
            st = self._allocator.stats()
            st.update({
                "capacity_rows": self.total_capacity,
                "extents": len(self._extents),
                "grow_events": self.grow_events,
                "shards": self.n_shards,
            })
            return st

    # -- slot allocation through per-shard page regions ------------------
    def _shard_regions(self, shard: int) -> list:
        return [(e, shard) for e in range(len(self._extents))]

    def _shard_of(self, slot: int) -> int:
        for ext in self._extents:
            if slot < ext.base + self.n_shards * ext.cap_per_shard:
                return (slot - ext.base) // ext.cap_per_shard
        raise IndexError(slot)

    def _shard_free(self, shard: int) -> int:
        if self._allocator.tenant_quota_pages is None:
            return self._shard_free_rows[shard]
        return self._allocator.free_slots_available(
            self._tenant, regions=self._shard_regions(shard))

    def _ensure_free(self, n: int) -> None:
        from pathway_tpu.engine.paged_store import PageQuotaExceeded

        capped = self._allocator.quota_capped_slots(self._tenant)
        if capped is not None and capped < n:
            # growth cannot help: the tenant's quota, not the pool, is
            # the limit (and an unguarded loop would grow forever)
            raise PageQuotaExceeded(
                f"tenant {self._tenant!r} needs {n} slots but its page "
                f"quota caps it at {capped} more")
        while self._allocator.free_slots_available(self._tenant) < n:
            self._grow()

    def _release_slot(self, slot: int) -> None:
        self._allocator.release_slot(slot)
        self._shard_free_rows[self._shard_of(slot)] += 1

    def _alloc_slot(self, key: Pointer) -> int:
        slot = self._key_to_slot.get(key)
        if slot is None:
            # balance by emptiest shard, exactly like the slab path
            shard = max(range(self.n_shards), key=self._shard_free)
            if self._shard_free(shard) == 0:
                self._ensure_free(1)
                shard = max(range(self.n_shards), key=self._shard_free)
            slot = self._allocator.take_slot(
                self._tenant, regions=self._shard_regions(shard))
            self._shard_free_rows[shard] -= 1
            self._key_to_slot[key] = slot
            self._slot_to_key[slot] = key
        return slot

    # -- device sync per extent ------------------------------------------
    def _sharding(self):
        import jax

        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(DATA_AXIS))

    def _zeros_sharded(self, shape, dtype):
        """Zero-establish a sharded array ON DEVICE when the runtime
        supports out_shardings (no host transfer); host zeros upload as
        the fallback."""
        import jax
        import jax.numpy as jnp

        sharding = self._sharding()
        try:
            return jax.jit(lambda: jnp.zeros(shape, dtype),
                           out_shardings=sharding)()
        except TypeError:
            return jax.device_put(np.zeros(shape, dtype), sharding)

    def _establish_extent(self, ext: _ShardExtent) -> None:
        if ext.vectors is not None:
            return
        import jax.numpy as jnp

        S, C, D = self.n_shards, ext.cap_per_shard, self.dim
        if self.dtype == "int8":
            ext.vectors = self._zeros_sharded((S, C, D), jnp.int8)
            ext.scales = self._zeros_sharded((S, C), jnp.float32)
            ext.vsq = self._zeros_sharded((S, C), jnp.float32)
        else:
            dt = jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32
            ext.vectors = self._zeros_sharded((S, C, D), dt)
        ext.valid = self._zeros_sharded((S, C), jnp.bool_)

    def _split_by_extent(self, idxs: np.ndarray):
        for ext in self._extents:
            span = self.n_shards * ext.cap_per_shard
            in_ext = (idxs >= ext.base) & (idxs < ext.base + span)
            if not in_ext.any():
                continue
            pos = np.flatnonzero(in_ext)
            yield ext, idxs[pos] - ext.base, pos

    def _flush_to_device(self):
        import jax.numpy as jnp

        for ext in self._extents:
            self._establish_extent(ext)
        if not self._dirty:
            return
        idxs = np.fromiter(self._dirty, dtype=np.int64)
        self._dirty.clear()
        for ext, local, pos in self._split_by_extent(idxs):
            rows_global = idxs[pos]
            sh, sl = local // ext.cap_per_shard, local % ext.cap_per_shard
            if self.dtype == "int8":
                q, scale, vsq = _quantize_i8_np(
                    self._host_vectors[rows_global])
                ext.vectors = ext.vectors.at[sh, sl].set(jnp.asarray(q))
                ext.scales = ext.scales.at[sh, sl].set(jnp.asarray(scale))
                ext.vsq = ext.vsq.at[sh, sl].set(jnp.asarray(vsq))
            else:
                rows = self._host_vectors[rows_global]
                if self.dtype == "bfloat16":
                    import ml_dtypes

                    rows = rows.astype(ml_dtypes.bfloat16)
                ext.vectors = ext.vectors.at[sh, sl].set(jnp.asarray(rows))
            ext.valid = ext.valid.at[sh, sl].set(
                jnp.asarray(self._host_valid[rows_global]))

    # -- multi-extent search ---------------------------------------------
    def _get_search_fn(self, k: int):
        caps = tuple(e.cap_per_shard for e in self._extents)
        bases = tuple(e.base for e in self._extents)
        cache_key = (k, caps, self.dtype)
        fn = self._search_fn_cache.get(cache_key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        metric = self.metric
        int8 = self.dtype == "int8"
        per_ext = 4 if int8 else 2
        score = self._local_scores

        def local_search(queries, *ops):
            # ops per extent: vectors (1,C,D), valid (1,C)[, scales, vsq]
            shard_id = jax.lax.axis_index(DATA_AXIS)
            cand_s, cand_i = [], []
            for e, (C, base) in enumerate(zip(caps, bases)):
                o = ops[e * per_ext:(e + 1) * per_ext]
                ex = (o[2][0], o[3][0]) if int8 else ()
                scores = score(queries, o[0][0], o[1][0], ex, metric, int8)
                s, i = jax.lax.top_k(scores, min(k, C))
                cand_s.append(s)
                # paged slot ids: extent base + this shard's block + row
                cand_i.append(base + shard_id * C + i)
            s = jnp.concatenate(cand_s, axis=1)
            gi = jnp.concatenate(cand_i, axis=1)
            # local merge BEFORE the gather: cross-chip traffic stays
            # n_shards x B x k however many extents exist
            s, pos = jax.lax.top_k(s, min(k, s.shape[1]))
            gi = jnp.take_along_axis(gi, pos, axis=1)
            all_s = jax.lax.all_gather(s, DATA_AXIS)
            all_i = jax.lax.all_gather(gi, DATA_AXIS)
            B = queries.shape[0]
            cs = jnp.transpose(all_s, (1, 0, 2)).reshape(B, -1)
            ci = jnp.transpose(all_i, (1, 0, 2)).reshape(B, -1)
            ms, mpos = jax.lax.top_k(cs, min(k, cs.shape[1]))
            return ms, jnp.take_along_axis(ci, mpos, axis=1)

        ext_specs = tuple(P(*axes) for axes, _rank
                          in search_operand_layout(self.dtype)[1:])
        in_specs = (P(),) + ext_specs * len(caps)
        shard_fn = _shard_map(
            local_search, mesh=self._mesh,
            in_specs=in_specs,
            out_specs=(P(), P()),
            check_vma=False,
        )
        fn = jax.jit(shard_fn)
        self._search_fn_cache[cache_key] = fn
        return fn

    def _device_topk(self, qmat, fetch_k: int):
        search_fn = self._get_search_fn(fetch_k)
        ops = []
        for ext in self._extents:
            ops += [ext.vectors, ext.valid]
            if self.dtype == "int8":
                ops += [ext.scales, ext.vsq]
        ts, ti = search_fn(qmat, *ops)
        return np.asarray(ts), np.asarray(ti)
