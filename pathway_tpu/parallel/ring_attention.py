"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has no attention kernels at all (SURVEY §2.5 — its "long
context" is long *streams*); pathway_tpu makes long-sequence attention
first-class for the embedder/LLM forward passes. Two standard schemes:

- ``ring_attention``: K/V blocks rotate around the mesh axis via
  ``lax.ppermute`` while each chip keeps its Q shard; softmax is
  accumulated online (flash-attention style: running max + denominator),
  so the full S×S score matrix never materialises and each step overlaps
  one block matmul with one ICI hop.
- ``ulysses_attention``: ``all_to_all`` re-shards from sequence-parallel
  to head-parallel, runs exact local attention over full sequence per
  head group, and re-shards back. Cheaper at moderate S, needs
  heads % n_shards == 0.

Both are pure-JAX over ``jax.shard_map`` — XLA lowers the collectives to
ICI ops. Inputs are (batch, seq, heads, head_dim) with seq sharded over
the mesh ``data`` axis (or any named axis passed in).
"""

from __future__ import annotations


from pathway_tpu.parallel.mesh import DATA_AXIS
from pathway_tpu.parallel.mesh import shard_map as _shard_map


def _online_block(q, k_blk, v_blk, m, l, o, mask=None):
    """One flash-style accumulation step. q (B,Sq,H,D); k/v (B,Sk,H,D);
    m,l (B,H,Sq); o (B,Sq,H,D)."""
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    # guard fully-masked rows (m_new == -inf) against NaNs
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(scores - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(jnp.where(jnp.isfinite(m), m - m_safe, -jnp.inf))
    corr = jnp.where(jnp.isfinite(corr), corr, 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * jnp.transpose(corr, (0, 2, 1))[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk)
    return m_new, l_new, o_new


def ring_attention(q, k, v, *, mesh=None, axis: str = DATA_AXIS,
                   causal: bool = False):
    """Exact attention with sequence sharded over ``axis``.

    q, k, v: (batch, seq, heads, head_dim), seq dim sharded. Returns the
    attention output with the same sharding.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.mesh import get_mesh

    if mesh is None:
        mesh = get_mesh()
    n = int(mesh.shape[axis])
    if q.shape[1] % n != 0:
        # shard_map would reject this with an opaque sharding error; the
        # static checker flags the same condition pre-execution (PWT102)
        raise ValueError(
            f"ring attention: sequence length {q.shape[1]} is not "
            f"divisible by the {axis!r} axis size {n} (PWT102) — pad the "
            f"sequence or shrink the axis")
    perm = [(i, (i + 1) % n) for i in range(n)]

    def local(q, k, v):
        B, Sq, H, D = q.shape
        my = jax.lax.axis_index(axis)
        m0 = jnp.full((B, H, Sq), -jnp.inf, dtype=jnp.float32)
        l0 = jnp.zeros((B, H, Sq), dtype=jnp.float32)
        o0 = jnp.zeros(q.shape, dtype=jnp.float32)

        q_pos = my * Sq + jnp.arange(Sq)

        def body(t, carry):
            k_blk, v_blk, m, l, o = carry
            if causal:
                src = (my - t) % n
                k_pos = src * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
                mask = q_pos[None, None, :, None] >= k_pos[None, None, None, :]
            else:
                mask = None
            m, l, o = _online_block(q, k_blk, v_blk, m, l, o, mask)
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
            return k_blk, v_blk, m, l, o

        k_blk, v_blk, m, l, o = jax.lax.fori_loop(
            0, n, body, (k, v, m0, l0, o0))
        denom = jnp.transpose(l, (0, 2, 1))[..., None]
        return (o / jnp.maximum(denom, 1e-30)).astype(q.dtype)

    spec = P(None, axis, None, None)
    fn = _shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, *, mesh=None, axis: str = DATA_AXIS,
                      causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style).

    Re-shards (B, S/n, H, D) → (B, S, H/n, D) with one all_to_all, runs
    exact attention per head group over the full sequence, and re-shards
    back. Requires heads % axis_size == 0.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from pathway_tpu.parallel.mesh import get_mesh

    if mesh is None:
        mesh = get_mesh()
    n = int(mesh.shape[axis])
    if q.shape[2] % n != 0:
        raise ValueError(
            f"ulysses attention: {q.shape[2]} heads not divisible by the "
            f"{axis!r} axis size {n} (PWT106) — pad heads to a multiple "
            f"of {n} or use ring attention")
    if q.shape[1] % n != 0:
        raise ValueError(
            f"ulysses attention: sequence length {q.shape[1]} is not "
            f"divisible by the {axis!r} axis size {n} (PWT102)")

    def local(q, k, v):
        # (B, S/n, H, D) → (B, S, H/n, D): split heads, concat seq
        def seq_to_head(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        def head_to_seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
        scale = qh.shape[-1] ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) * scale
        if causal:
            S = qh.shape[1]
            mask = jnp.tril(jnp.ones((S, S), dtype=bool))
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vh.dtype), vh)
        return head_to_seq(out)

    spec = P(None, axis, None, None)
    fn = _shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check_vma=False)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = False):
    """Unsharded exact attention for testing parity."""
    import jax
    import jax.numpy as jnp

    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
