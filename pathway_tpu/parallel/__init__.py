"""pathway_tpu.parallel — device-mesh parallelism layer.

The TPU-native replacement for the reference's distributed machinery
(timely `communication` crate: external/timely-dataflow/communication/ —
worker threads + TCP exchange channels; worker/cluster config
src/engine/dataflow/config.rs:62-127). Instead of N identical workers
exchanging rows over sockets, pathway_tpu scales by sharding device state
(vector slabs, grouped aggregates) over a `jax.sharding.Mesh` and letting
XLA insert ICI collectives (psum / all_gather / ppermute / all_to_all)
inside jitted steps.

Axis conventions (used across the framework):
- ``data``  — batch / keyspace shards (the reference's worker shards,
  src/engine/dataflow/shard.rs:6-20)
- ``model`` — tensor-parallel shards of model weights (absent in the
  reference — SURVEY §2.5 — but first-class here)
A sequence axis for ring/Ulysses attention reuses ``data`` by default.
"""

from __future__ import annotations

from pathway_tpu.parallel.mesh import (
    MeshConfig,
    current_mesh,
    get_mesh,
    make_mesh,
    replicated,
    shard_batch,
    use_mesh,
)
from pathway_tpu.parallel.sharded_knn import ShardedKnnIndex
from pathway_tpu.parallel.ring_attention import (
    ring_attention,
    ulysses_attention,
)

__all__ = [
    "MeshConfig",
    "make_mesh",
    "get_mesh",
    "use_mesh",
    "current_mesh",
    "shard_batch",
    "replicated",
    "ShardedKnnIndex",
    "ring_attention",
    "ulysses_attention",
]

from pathway_tpu.parallel import pipeline  # noqa: F401
