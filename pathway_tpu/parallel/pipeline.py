"""Pipeline parallelism: GPipe-style microbatched stage execution over a
mesh axis.

The reference has no pipeline parallelism (SURVEY §2.5: ABSENT — its
dataflow stages are operators, not weight partitions); this is part of the
TPU-first training story alongside dp/tp/ep (models/train.py) and
sequence-parallel ring attention (parallel/ring_attention.py).

Design: the transformer's L homogeneous blocks are stacked on a leading
layer axis and sharded over the ``pipe`` mesh axis, so each device holds
L/S consecutive blocks. Microbatches flow through the classic GPipe
schedule inside ONE jitted shard_map: at step t every stage applies its
blocks to its current activation, then `lax.ppermute` rotates activations
to the next stage over ICI. Stage 0 injects microbatch t while t < M; the
last stage collects output t-(S-1). Total steps M + S - 1; bubble fraction
(S-1)/(M+S-1) — amortized by more microbatches, exactly the standard
schedule. Static shapes throughout; the step loop is a `lax.scan`.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pathway_tpu.parallel.mesh import shard_map as _shard_map

PIPE_AXIS = "pipe"


def stack_stage_params(layer_params: list) -> dict:
    """[per-layer pytree] -> one pytree with a leading layer axis, ready to
    shard over the pipe axis."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *layer_params)


def make_pipeline_fn(mesh, block_fn: Callable, *, axis: str = PIPE_AXIS,
                     extra_spec=P()):
    """Build ``run(stacked_params, microbatches, *extra) -> outputs``.

    - ``stacked_params``: pytree with leading layer axis (length L,
      divisible by the pipe-axis size); sharded over ``axis``.
    - ``microbatches``: (M, mb, ...) activations, replicated.
    - ``block_fn(layer_params, x, extra) -> x``: one transformer block.
    - ``extra``: ONE replicated side input shared by every microbatch
      (e.g. an attention mask; per-microbatch side inputs belong inside
      ``microbatches`` itself).

    Output (M, mb, ...) is replicated (psum-broadcast from the last
    stage). Parity with sequential layer application is exact.
    """
    n_stages = int(mesh.shape[axis])

    def stage_apply(local_params, x, extra):
        # apply this stage's L/S blocks in order (scan over the local
        # layer axis keeps one compiled block body)
        def body(h, layer):
            return block_fn(layer, h, extra), None

        out, _ = lax.scan(body, x, local_params)
        return out

    @functools.partial(
        _shard_map, mesh=mesh,
        in_specs=(P(axis), P(), extra_spec),
        out_specs=P(),
        check_vma=False)
    def run(stacked, microbatches, extra):
        stage = lax.axis_index(axis)
        m = microbatches.shape[0]
        state = jnp.zeros_like(microbatches[0])
        outputs = jnp.zeros_like(microbatches)

        def step(carry, t):
            state, outputs = carry
            inject = microbatches[jnp.clip(t, 0, m - 1)]
            x = jnp.where(stage == 0, inject, state)
            out = stage_apply(stacked, x, extra)
            oi = t - (n_stages - 1)
            collect = (stage == n_stages - 1) & (oi >= 0)
            outputs = jnp.where(
                collect,
                outputs.at[jnp.clip(oi, 0, m - 1)].set(out),
                outputs)
            state = lax.ppermute(
                out, axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (state, outputs), None

        (state, outputs), _ = lax.scan(
            step, (state, outputs), jnp.arange(m + n_stages - 1))
        # results live on the last stage only; broadcast for replicated out
        return lax.psum(
            jnp.where(stage == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), axis)

    def wrapper(stacked_params, microbatches, *extra):
        if len(extra) > 1:
            raise TypeError(
                "make_pipeline_fn supports ONE replicated side input; pack "
                f"extras into a single pytree (got {len(extra)})")
        leaves = jax.tree_util.tree_leaves(stacked_params)
        if leaves and leaves[0].shape[0] % n_stages != 0:
            # the leading layer axis shards over the pipe axis; a
            # non-divisible stack would silently mis-shard or fail deep in
            # shard_map — same condition the static checker flags (PWT102)
            raise ValueError(
                f"pipeline: {leaves[0].shape[0]} stacked layers are not "
                f"divisible by the {n_stages}-stage pipe axis (PWT102) — "
                f"pad the layer stack or change the stage count")
        packed = extra[0] if extra else jnp.zeros((), jnp.float32)
        return run(stacked_params, microbatches, packed)

    return wrapper


def sequential_encoder_blocks(layers, x, mask, config):
    """Reference computation the pipeline must match: the encoder's blocks
    applied in order (shared by tests and the driver dryrun)."""
    from pathway_tpu.models.encoder import (_attention_block,
                                            _dense_attention, _mlp_block)

    x = x.astype(config.compute_dtype)
    for layer in layers:
        x = _attention_block(x, layer["attn"], mask, config,
                             _dense_attention)
        x = _mlp_block(x, layer["mlp"], config)
    return x


def pipeline_encoder_blocks(mesh, config, *, axis: str = PIPE_AXIS):
    """Pipeline runner for the flagship encoder's transformer blocks
    (models/encoder.py): ``run(stacked_layer_params, x_microbatches, mask)``
    where x is the post-embedding hidden state. Embeddings and pooling stay
    replicated outside the pipeline (they are a tiny fraction of the
    FLOPs; the blocks are where pipelining pays)."""
    from pathway_tpu.models.encoder import (_attention_block,
                                            _dense_attention, _mlp_block)

    def block_fn(layer, x, mask):
        x = _attention_block(x, layer["attn"], mask, config,
                             _dense_attention)
        return _mlp_block(x, layer["mlp"], config)

    run = make_pipeline_fn(mesh, block_fn, axis=axis)

    def wrapped(stacked_params, microbatches, mask):
        # blocks compute (and emit) compute_dtype; the scan carry must be
        # dtype-stable, so activations enter the pipeline already cast
        x = microbatches.astype(config.compute_dtype)
        return run(stacked_params, x, mask)

    return wrapped
