"""Scalar/columnar operation implementations for the expression compiler.

The runtime counterpart of the reference's typed expression interpreter
(src/engine/expression.rs, ops mirrored in python/pathway/engine.pyi:211-390):
binary/unary ops per type, casts/conversions, and the dt/str/num method
registry. Implementations are scalar; the compiler maps them over batches
(numpy vectorization for numeric columns happens in the compiler).
"""

from __future__ import annotations

import datetime
import math
import operator
from typing import Any, Callable

import numpy as np
import pandas as pd

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.error import ERROR
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import Pointer


def _num_binop(fn):
    def impl(a, b):
        return fn(a, b)

    return impl


def _div(a, b):
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
            and not isinstance(a, bool) and not isinstance(b, bool):
        return a / b
    return a / b


def _matmul(a, b):
    return np.matmul(np.asarray(a), np.asarray(b))


def _eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return bool(np.array_equal(a, b))
    return a == b


def _ne(a, b):
    return not _eq(a, b)


BINARY_OPS: dict[str, Callable[[Any, Any], Any]] = {
    "+": operator.add,
    "-": operator.sub,
    "*": operator.mul,
    "/": _div,
    "//": operator.floordiv,
    "%": operator.mod,
    "**": operator.pow,
    "@": _matmul,
    "==": _eq,
    "!=": _ne,
    "<": operator.lt,
    "<=": operator.le,
    ">": operator.gt,
    ">=": operator.ge,
    "&": lambda a, b: (a & b) if not isinstance(a, bool) or not isinstance(b, bool) else (a and b),
    "|": lambda a, b: (a | b) if not isinstance(a, bool) or not isinstance(b, bool) else (a or b),
    "^": operator.xor,
}

UNARY_OPS: dict[str, Callable[[Any], Any]] = {
    "-": operator.neg,
    "~": lambda a: (not a) if isinstance(a, bool) else ~a,
}

# ops safe to evaluate via numpy on whole numeric columns
NUMPY_SAFE_BINOPS = {"+", "-", "*", "/", "<", "<=", ">", ">=", "==", "!=", "&", "|", "^"}


def cast_value(value: Any, target: dt.DType) -> Any:
    if value is None or value is ERROR:
        return value
    t = dt.unoptionalize(target)
    if t is dt.INT:
        return int(value)
    if t is dt.FLOAT:
        return float(value)
    if t is dt.BOOL:
        return bool(value)
    if t is dt.STR:
        return to_string(value)
    return value


def convert_value(value: Any, target: dt.DType, unwrap: bool = False) -> Any:
    """Runtime conversion (as_int/as_float/... — works on Json/Any)."""
    if value is ERROR:
        return value
    if isinstance(value, Json):
        value = value.value
    if value is None:
        if unwrap:
            raise ValueError("cannot convert None")
        return None
    t = dt.unoptionalize(target)
    if t is dt.INT:
        if isinstance(value, bool) or not isinstance(value, (int, np.integer)):
            if isinstance(value, (float, np.floating)) and float(value).is_integer():
                return int(value)
            raise ValueError(f"cannot convert {value!r} to int")
        return int(value)
    if t is dt.FLOAT:
        if isinstance(value, bool) or not isinstance(value, (int, float, np.integer, np.floating)):
            raise ValueError(f"cannot convert {value!r} to float")
        return float(value)
    if t is dt.BOOL:
        if not isinstance(value, (bool, np.bool_)):
            raise ValueError(f"cannot convert {value!r} to bool")
        return bool(value)
    if t is dt.STR:
        if not isinstance(value, str):
            raise ValueError(f"cannot convert {value!r} to str")
        return value
    if t is dt.DURATION:
        if not isinstance(value, (datetime.timedelta, pd.Timedelta)):
            raise ValueError(f"cannot convert {value!r} to Duration")
        return value
    return value


def to_string(value: Any) -> str:
    if isinstance(value, Json):
        return value.dumps()
    if isinstance(value, float) and value.is_integer() and not math.isinf(value):
        return repr(value)
    if isinstance(value, Pointer):
        return str(value)
    return str(value)


def get_item(obj: Any, index: Any, default: Any, check: bool) -> Any:
    if obj is ERROR or index is ERROR:
        return ERROR
    if obj is None:
        return default if check else None
    try:
        if isinstance(obj, Json):
            if check:
                got = obj.get(index, _MISSING)
                return default if got is _MISSING else got
            return obj[index]
        if isinstance(obj, np.ndarray):
            return dt.normalize_scalar(obj[index])
        return obj[index]
    except (KeyError, IndexError, TypeError):
        if check:
            return default
        raise


class _Missing:
    pass


_MISSING = _Missing()

# ---------------------------------------------------------------------------
# dt/str/num method registry — scalar implementations
# ---------------------------------------------------------------------------


def _ts(v):
    """Normalize datetime-ish to pandas Timestamp."""
    if isinstance(v, pd.Timestamp):
        return v
    return pd.Timestamp(v)


def _td(v):
    if isinstance(v, pd.Timedelta):
        return v
    return pd.Timedelta(v)


def _strptime(s, fmt, contains_timezone=False):
    # pandas handles %z; naive otherwise
    ts = pd.Timestamp(datetime.datetime.strptime(s, fmt))
    return ts


def _dt_timestamp(v, unit="ns"):
    ts = _ts(v)
    if ts.tzinfo is not None:
        ns = ts.value
    else:
        ns = ts.value
    div = {"ns": 1, "us": 1_000, "ms": 1_000_000, "s": 1_000_000_000}[unit]
    return ns // div if div > 1 else ns


def _from_timestamp(v, unit="ns"):
    return pd.Timestamp(v, unit=unit)


def _utc_from_timestamp(v, unit="ns"):
    return pd.Timestamp(v, unit=unit, tz="UTC")


METHODS: dict[str, Callable] = {
    # ---- generic
    "to_string": to_string,
    # ---- num
    "num.abs": abs,
    "num.round": lambda v, decimals=0: round(v, decimals) if decimals else (
        float(round(v)) if isinstance(v, float) else round(v)),
    "num.fill_na": lambda v, default: default
    if v is None or (isinstance(v, float) and math.isnan(v))
    else v,
    # ---- str
    "str.lower": lambda s: s.lower(),
    "str.upper": lambda s: s.upper(),
    "str.reversed": lambda s: s[::-1],
    "str.len": lambda s: len(s),
    "str.strip": lambda s, chars=None: s.strip(chars),
    "str.lstrip": lambda s, chars=None: s.lstrip(chars),
    "str.rstrip": lambda s, chars=None: s.rstrip(chars),
    "str.startswith": lambda s, p: s.startswith(p),
    "str.endswith": lambda s, p: s.endswith(p),
    "str.swapcase": lambda s: s.swapcase(),
    "str.title": lambda s: s.title(),
    "str.capitalize": lambda s: s.capitalize(),
    "str.casefold": lambda s: s.casefold(),
    "str.count": lambda s, sub, start=None, end=None: s.count(
        sub, start if start is not None else 0, end if end is not None else len(s)),
    "str.find": lambda s, sub, start=None, end=None: s.find(
        sub, start if start is not None else 0, end if end is not None else len(s)),
    "str.rfind": lambda s, sub, start=None, end=None: s.rfind(
        sub, start if start is not None else 0, end if end is not None else len(s)),
    "str.removeprefix": lambda s, p: s.removeprefix(p),
    "str.removesuffix": lambda s, p: s.removesuffix(p),
    "str.replace": lambda s, old, new, count=-1: s.replace(old, new, count),
    "str.split": lambda s, sep=None, maxsplit=-1: tuple(s.split(sep, maxsplit)),
    "str.rsplit": lambda s, sep=None, maxsplit=-1: tuple(s.rsplit(sep, maxsplit)),
    "str.slice": lambda s, start, end: s[start:end],
    "str.parse_int": lambda s, optional=False: _parse(int, s, optional),
    "str.parse_float": lambda s, optional=False: _parse(float, s, optional),
    "str.parse_bool": lambda s, true_values=("on", "true", "yes", "1"),
    false_values=("off", "false", "no", "0"), optional=False: _parse_bool(
        s, true_values, false_values, optional),
    # ---- dt (datetime components)
    "dt.nanosecond": lambda v: _ts(v).nanosecond + _ts(v).microsecond * 1000 * 0,
    "dt.microsecond": lambda v: _ts(v).microsecond,
    "dt.millisecond": lambda v: _ts(v).microsecond // 1000,
    "dt.second": lambda v: _ts(v).second,
    "dt.minute": lambda v: _ts(v).minute,
    "dt.hour": lambda v: _ts(v).hour,
    "dt.day": lambda v: _ts(v).day,
    "dt.month": lambda v: _ts(v).month,
    "dt.year": lambda v: _ts(v).year,
    "dt.weekday": lambda v: int(_ts(v).weekday()),
    "dt.timestamp": _dt_timestamp,
    "dt.strftime": lambda v, fmt: _ts(v).strftime(fmt),
    "dt.strptime": _strptime,
    "dt.from_timestamp": _from_timestamp,
    "dt.utc_from_timestamp": _utc_from_timestamp,
    "dt.to_utc": lambda v, from_tz: _ts(v).tz_localize(from_tz).tz_convert("UTC"),
    "dt.to_naive_in_timezone": lambda v, tz: _ts(v).tz_convert(tz).tz_localize(None),
    "dt.round": lambda v, dur: _ts(v).round(_td(dur)),
    "dt.floor": lambda v, dur: _ts(v).floor(_td(dur)),
    # ---- dt (duration accessors)
    "dt.nanoseconds": lambda v: _td(v).value,
    "dt.microseconds": lambda v: _td(v).value // 1_000,
    "dt.milliseconds": lambda v: _td(v).value // 1_000_000,
    "dt.seconds": lambda v: _td(v).value // 1_000_000_000,
    "dt.minutes": lambda v: _td(v).value // 60_000_000_000,
    "dt.hours": lambda v: _td(v).value // 3_600_000_000_000,
    "dt.days": lambda v: _td(v).value // 86_400_000_000_000,
    "dt.weeks": lambda v: _td(v).value // 604_800_000_000_000,
    "dt.add_duration_in_timezone": lambda v, dur, tz: (
        _ts(v).tz_localize(tz) + _td(dur)).tz_localize(None)
    if _ts(v).tzinfo is None
    else _ts(v) + _td(dur),
    "dt.subtract_duration_in_timezone": lambda v, dur, tz: (
        _ts(v).tz_localize(tz) - _td(dur)).tz_localize(None)
    if _ts(v).tzinfo is None
    else _ts(v) - _td(dur),
    "dt.subtract_date_time_in_timezone": lambda a, b, tz: (
        _ts(a).tz_localize(tz) - _ts(b).tz_localize(tz)),
}


def _parse(fn, s, optional):
    try:
        return fn(s.strip()) if isinstance(s, str) else fn(s)
    except (ValueError, TypeError):
        if optional:
            return None
        raise


def _parse_bool(s, true_values, false_values, optional):
    low = s.strip().lower() if isinstance(s, str) else s
    if low in true_values:
        return True
    if low in false_values:
        return False
    if optional:
        return None
    raise ValueError(f"cannot parse {s!r} as bool")
