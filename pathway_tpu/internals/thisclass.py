"""`pw.this`, `pw.left`, `pw.right` deferred references
(reference: python/pathway/internals/thisclass.py)."""

from __future__ import annotations

from pathway_tpu.internals.expression import (
    ColumnReference,
    IdExpression,
    PointerExpression,
)


class ThisRef:
    """Placeholder table; resolved against a concrete table at use site."""

    def __init__(self, kind: str = "this"):
        self._kind = kind

    @property
    def id(self):
        return IdExpression(self)

    def __getattr__(self, name: str):
        if name.startswith("__") or name == "_kind":
            raise AttributeError(name)
        return ColumnReference(self, name)

    def __getitem__(self, name):
        if isinstance(name, (list, tuple)):
            return [self[n] for n in name]
        if isinstance(name, ColumnReference):
            return ColumnReference(self, name.name)
        return ColumnReference(self, name)

    def pointer_from(self, *args, optional=False, instance=None):
        return PointerExpression(self, *args, optional=optional, instance=instance)

    def without(self, *cols):
        return ThisWithout(self, cols)

    def __iter__(self):
        raise TypeError(f"pw.{self._kind} is not iterable")

    def __repr__(self):
        return f"<pw.{self._kind}>"


class ThisWithout(ThisRef):
    def __init__(self, base, cols):
        super().__init__(getattr(base, "_kind", "this"))
        self._base = base
        self._cols = tuple(
            c.name if isinstance(c, ColumnReference) else c for c in cols
        )


this = ThisRef("this")
left = ThisRef("left")
right = ThisRef("right")


def resolve_this(kind_map: dict, expr):
    """Substitute ThisRef tables inside an expression with real tables.

    kind_map: {"this": table} or {"left": t1, "right": t2, "this": joined}.
    """
    from pathway_tpu.internals import expression as ex

    if isinstance(expr, ex.ColumnReference):
        tab = expr.table
        if isinstance(tab, ThisRef):
            target = kind_map.get(tab._kind)
            if target is None:
                raise ValueError(f"pw.{tab._kind} cannot be used here")
            if isinstance(expr, ex.IdExpression):
                return ex.IdExpression(target)
            return target[expr.name]
        return expr
    if isinstance(expr, ex.PointerExpression) and isinstance(expr._table, ThisRef):
        target = kind_map.get(expr._table._kind)
        new = object.__new__(ex.PointerExpression)
        new.__dict__ = dict(expr.__dict__)
        new._table = target
        new._args = tuple(resolve_this(kind_map, a) for a in expr._args)
        if expr._instance is not None:
            new._instance = resolve_this(kind_map, expr._instance)
        return new
    # generic: rebuild children
    return _rebuild(kind_map, expr)


def _rebuild(kind_map, expr):
    from pathway_tpu.internals import expression as ex

    if not isinstance(expr, ex.ColumnExpression):
        return expr
    deps = expr._deps
    if not deps:
        return expr
    new = object.__new__(type(expr))
    new.__dict__ = dict(expr.__dict__)
    for attr, val in list(new.__dict__.items()):
        if isinstance(val, ex.ColumnExpression):
            new.__dict__[attr] = resolve_this(kind_map, val)
        elif isinstance(val, tuple) and any(
            isinstance(v, ex.ColumnExpression) for v in val
        ):
            new.__dict__[attr] = tuple(
                resolve_this(kind_map, v) if isinstance(v, ex.ColumnExpression) else v
                for v in val
            )
        elif isinstance(val, dict) and any(
            isinstance(v, ex.ColumnExpression) for v in val.values()
        ):
            new.__dict__[attr] = {
                k: resolve_this(kind_map, v) if isinstance(v, ex.ColumnExpression) else v
                for k, v in val.items()
            }
    return new
