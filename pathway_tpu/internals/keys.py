"""128-bit row keys (Pointers) and deterministic hashing.

Rebuild of the reference's ``Key`` (src/engine/value.rs:41 — xxh3-derived
u128 ids) and the ``pointer_from`` derivation. We use blake2b-128 over a
canonical encoding: deterministic across processes/hosts, so key-based
sharding over a TPU mesh is stable without coordination.

Sharding mirrors src/engine/dataflow/shard.rs:6 — ``shard = key & MASK`` —
except the mask is the mesh's data-axis size, not a licensed 8-worker cap
(reference caps at MAX_WORKERS=8, src/engine/dataflow/config.rs:7; we don't).
"""

from __future__ import annotations

import hashlib
import math
import struct
from typing import Any, Iterable

import numpy as np

_SALT = b"pathway-tpu-key-v1"


class Pointer(int):
    """An opaque 128-bit row id. Subclasses int for cheap hashing/dict keys."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"^{self:032X}"[:12] + "..."

    def __str__(self) -> str:
        return f"^{_b64ish(self)}"

    @property
    def lo(self) -> int:
        return int(self) & 0xFFFFFFFFFFFFFFFF

    @property
    def hi(self) -> int:
        return int(self) >> 64


_ALPHABET = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"


def _b64ish(v: int) -> str:
    # short readable digest for debug printing (like reference's base32 keys)
    out = []
    v = int(v) & ((1 << 128) - 1)
    for _ in range(14):
        out.append(_ALPHABET[v % 36])
        v //= 36
    return "".join(reversed(out))


def _encode_value(value: Any, out: list) -> None:
    """Canonical byte encoding of an engine value for hashing.

    Hot path: one exact-type dict dispatch (``_ENCODERS``) instead of an
    isinstance chain — this runs once per value per key derivation
    (~millions of calls per 100k-row tick). Subclasses and numpy scalar
    types miss the dict and take the full chain below, which stays the
    single source of encoding truth for them."""
    enc = _ENCODERS.get(type(value))
    if enc is not None:
        enc(value, out)
        return
    _encode_value_slow(value, out)


def _enc_none(value, out):
    out.append(b"\x00")


def _enc_bool(value, out):
    out.append(b"\x01\x01" if value else b"\x01\x00")


def _enc_pointer(value, out):
    out.append(b"\x02" + int(value).to_bytes(16, "little"))


def _enc_int(value, out):
    v = int(value)
    if -(2**63) <= v < 2**63:
        out.append(b"\x03" + struct.pack("<q", v))
    else:
        # arbitrary-precision ints (e.g. raw 128-bit pointer values)
        b = v.to_bytes((v.bit_length() + 8) // 8, "little", signed=True)
        out.append(b"\x0b" + struct.pack("<q", len(b)) + b)


def _enc_float(value, out):
    f = float(value)
    if math.isfinite(f) and f == int(f) and abs(f) < 2**62:
        # ints and equal floats hash identically (reference: HashInto for Value)
        out.append(b"\x03" + struct.pack("<q", int(f)))
    else:
        out.append(b"\x04" + struct.pack("<d", f))


def _enc_str(value, out):
    b = value.encode()
    out.append(b"\x05" + struct.pack("<q", len(b)) + b)


def _enc_bytes(value, out):
    out.append(b"\x06" + struct.pack("<q", len(value)) + value)


def _enc_tuple(value, out):
    out.append(b"\x07" + struct.pack("<q", len(value)))
    for v in value:
        _encode_value(v, out)


def _enc_ndarray(value, out):
    out.append(b"\x08" + str(value.dtype).encode() + struct.pack(
        "<q", value.ndim) + value.shape.__repr__().encode() + value.tobytes())


_ENCODERS = {
    type(None): _enc_none,
    bool: _enc_bool,
    Pointer: _enc_pointer,
    int: _enc_int,
    float: _enc_float,
    str: _enc_str,
    bytes: _enc_bytes,
    tuple: _enc_tuple,
    np.ndarray: _enc_ndarray,
}


def _encode_value_slow(value: Any, out: list) -> None:
    """Full chain for types outside _ENCODERS (numpy scalars, subclasses,
    Json, arbitrary objects). MUST encode identically to the fast
    encoders for any value both can see."""
    if value is None:
        out.append(b"\x00")
    elif value is True:
        out.append(b"\x01\x01")
    elif value is False:
        out.append(b"\x01\x00")
    elif isinstance(value, Pointer):
        _enc_pointer(value, out)
    elif isinstance(value, (int, np.integer)):
        _enc_int(value, out)
    elif isinstance(value, (float, np.floating)):
        _enc_float(value, out)
    elif isinstance(value, str):
        _enc_str(value, out)
    elif isinstance(value, bytes):
        _enc_bytes(value, out)
    elif isinstance(value, tuple):
        _enc_tuple(value, out)
    elif isinstance(value, np.ndarray):
        _enc_ndarray(value, out)
    else:
        from pathway_tpu.internals.json import Json

        if isinstance(value, Json):
            b = value.dumps().encode()
            out.append(b"\x09" + struct.pack("<q", len(b)) + b)
        else:
            b = repr(value).encode()
            out.append(b"\x0a" + struct.pack("<q", len(b)) + b)


_HASH_CACHE: dict = {}
_HASH_CACHE_MAX = 1 << 20


def hash_values(*values: Any) -> Pointer:
    """Deterministic 128-bit key from a tuple of values (ref_scalar analogue).

    Memoized: dataflow key spaces repeat heavily (every join/group output
    key and exchange route hashes the same few thousand values tick after
    tick), and encode+blake2b is ~16 µs while a dict hit is ~0.2 µs. The
    cache key is type-qualified because ``True == 1 == 1.0`` as dict keys
    but bool encodes differently (int vs equal float intentionally encode
    the SAME, so their sharing a cache slot is correct)."""
    try:
        ck = (values, tuple(map(type, values)))
        cached = _HASH_CACHE.get(ck)
        if cached is not None:
            return cached
    except TypeError:  # unhashable member (ndarray, Json, ...)
        ck = None
    out: list = []
    for v in values:
        _encode_value(v, out)
    digest = hashlib.blake2b(b"".join(out), digest_size=16, key=_SALT).digest()
    result = Pointer(int.from_bytes(digest, "little"))
    if ck is not None and len(_HASH_CACHE) < _HASH_CACHE_MAX:
        _HASH_CACHE[ck] = result
    return result


def hash_values_uncached(*values: Any) -> Pointer:
    """hash_values minus the memo cache, for callers whose keys are
    unique by construction (e.g. per-row source ids that embed a row
    index): the cache tuple build + miss + insert is pure overhead there
    and evicts genuinely-repeating dataflow keys. Identical bytes →
    identical Pointer as hash_values."""
    out: list = []
    for v in values:
        _encode_value(v, out)
    digest = hashlib.blake2b(b"".join(out), digest_size=16, key=_SALT).digest()
    return Pointer(int.from_bytes(digest, "little"))


def ref_scalar(*args: Any, optional: bool = False) -> Pointer:
    """Public ``pw.this.pointer_from`` scalar variant."""
    return hash_values(*args)


_MASK128 = (1 << 128) - 1
_MIX_A = 0x9E3779B97F4A7C15F39CC0605CEDC835
_MIX_B = 0xC2B2AE3D27D4EB4F165667B19E3779F9
_MIX_NONE = 0x6C62272E07BB014262B821756295C58D  # stands in for a missing side


def mix_pointers(a: int | None, b: int | None) -> Pointer:
    """Deterministic 128-bit combine of two (blake2b-uniform) pointers.

    The join output key — hash(left id, right id), reference
    dataflow.rs:2371-2379 — is recomputed for every output row on every
    affected-group delta; pointers are already uniform 128-bit digests, so
    a multiply-xor mix preserves uniformity at ~40x less cost than
    re-encoding + blake2b (hot-path measurement in bench.py bench_etl)."""
    x = _MIX_NONE if a is None else int(a)
    y = _MIX_NONE + 1 if b is None else int(b)
    x = (x * _MIX_A) & _MASK128
    y = (y * _MIX_B) & _MASK128
    z = (x ^ (y >> 63) ^ (y << 65)) & _MASK128
    z = (z * _MIX_A) & _MASK128
    return Pointer(z ^ (z >> 64))


_SEQ_NAMESPACE = hash_values("pathway-tpu/sequential")


def sequential_key(counter: int, salt: Any = 0) -> Pointer:
    return hash_values(_SEQ_NAMESPACE, salt, counter)


_INT_RANGE = 1 << 62


def canonical_shard_value(v: Any):
    """Canonical raw form of a value used as a route/state key.

    ``hash_values`` deliberately encodes equal ints and floats (and their
    numpy scalar forms) identically, so raw-value keying must collapse the
    same equivalence classes: integral floats and numpy scalars map to the
    python int/float, NaN (!= itself, so useless as a dict key) maps to
    its hash, and anything exotic maps to its hash. Bools stay raw — they
    equal their int twins as dict keys, which is safe for ROUTE caching
    (consistent, merely co-locates two groups) but NOT for join-state
    keying; join key functions hash bools before calling this."""
    if v is None:
        return v
    cls = v.__class__
    if cls is str or cls is int or cls is Pointer or cls is bool:
        return v
    if cls is float:
        if v != v:
            return hash_values(v)
        if v.is_integer() and -_INT_RANGE < v < _INT_RANGE:
            return int(v)
        return v
    if isinstance(v, np.integer):
        return int(v)
    if isinstance(v, np.floating):
        f = float(v)
        if f != f:
            return hash_values(f)
        if f.is_integer() and -_INT_RANGE < f < _INT_RANGE:
            return int(f)
        return f
    # subclasses of the raw-pass classes (np.str_, IntEnum, Pointer
    # subtypes) canonicalize to the base so they key identically to their
    # plain twins — hash_values encodes them identically too
    if isinstance(v, Pointer):
        return v
    if isinstance(v, str):
        return str(v)
    if isinstance(v, int):  # bool was exact-checked above; can't subclass
        return int(v)
    return hash_values(v)


def shard_of(key: Pointer, n_shards: int) -> int:
    return int(key) % n_shards


def shard_array(keys: Iterable[Pointer], n_shards: int) -> np.ndarray:
    return np.fromiter((int(k) % n_shards for k in keys), dtype=np.int64)


def keys_to_u64(keys: Iterable[Pointer]) -> np.ndarray:
    """Lossy 64-bit projection used for device-side routing tensors."""
    return np.fromiter(
        (int(k) & 0xFFFFFFFFFFFFFFFF for k in keys),
        dtype=np.uint64,
    )
