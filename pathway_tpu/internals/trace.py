"""User-frame attribution for operator errors.

Rebuild of the reference's trace machinery (python/pathway/internals/trace.py:144
+ ``EngineErrorWithTrace`` re-raising at graph_runner/__init__.py:216-228):
each Table operator captures the first stack frame *outside* the framework at
build time; when the engine later fails inside that operator, the error is
re-raised pointing at the user's line, not the scheduler internals.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass(frozen=True)
class Trace:
    file_name: str
    line_number: int
    function: str
    line: str

    def __str__(self) -> str:
        return (f'  File "{self.file_name}", line {self.line_number}, '
                f"in {self.function}\n    {self.line}")


def trace_user_frame() -> Trace | None:
    """The innermost stack frame that is not framework code.

    Walks raw frames (sys._getframe) instead of traceback.extract_stack():
    Plan construction calls this once per operator, and extracting the whole
    stack with source lines per call would dominate graph-build time."""
    import linecache
    import sys

    frame = sys._getframe(1)
    pkg_prefix = _PKG_ROOT + os.sep
    while frame is not None:
        fname = os.path.abspath(frame.f_code.co_filename)
        if not fname.startswith(pkg_prefix) and "<frozen" not in fname:
            line = linecache.getline(fname, frame.f_lineno).strip()
            return Trace(frame.f_code.co_filename, frame.f_lineno,
                         frame.f_code.co_name, line)
        frame = frame.f_back
    return None


def add_trace_note(e: BaseException, trace: Trace | None,
                   operator: str = "") -> None:
    """Attach operator + user-frame context to an exception in place,
    preserving its type (PEP 678 notes; reference add_pathway_trace_note).
    Idempotent per operator."""
    note = f"in operator {operator!r}" if operator else "in engine operator"
    if trace is not None:
        note += f"\noccurred here:\n{trace}"
    if note not in getattr(e, "__notes__", ()):
        if hasattr(e, "add_note"):
            e.add_note(note)
        else:  # Python < 3.11: emulate PEP 678 storage
            notes = getattr(e, "__notes__", None)
            if notes is None:
                notes = []
                e.__notes__ = notes
            notes.append(note)


