"""Minimal JMESPath-subset evaluator for metadata filters.

The reference filters index hits with JMESPath over metadata JSON
(src/external_integration/mod.rs:364 DerivedFilteredSearchIndex; xpack docs
use e.g. ``contains(path, 'foo')``, ``globmatch('**/*.pdf', path)``,
``modified_at >= `1700000000```). This evaluator covers that working subset:
dot paths, (back)quoted/number/string literals, ==/!=/<=/>=/</>,
&&/||/!, parentheses, and the functions contains() / globmatch().
"""

from __future__ import annotations

import re
from typing import Any

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<lit_backtick>`[^`]*`)
  | (?P<string>'[^']*'|"[^"]*")
  | (?P<number>-?\d+(\.\d+)?)
  | (?P<op>==|!=|<=|>=|&&|\|\||[<>()!,])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_\.\-]*)
""", re.VERBOSE)


def _tokenize(expr: str):
    out = []
    pos = 0
    while pos < len(expr):
        m = _TOKEN_RE.match(expr, pos)
        if m is None:
            raise ValueError(f"bad filter syntax at {expr[pos:]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.i = 0

    def peek(self):
        return self.tokens[self.i]

    def next(self):
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, value):
        kind, tok = self.next()
        if tok != value:
            raise ValueError(f"expected {value!r}, got {tok!r}")

    # expr := or_expr
    def parse(self):
        node = self.parse_or()
        if self.peek()[0] != "eof":
            raise ValueError(f"trailing tokens: {self.peek()!r}")
        return node

    def parse_or(self):
        node = self.parse_and()
        while self.peek()[1] == "||":
            self.next()
            node = ("or", node, self.parse_and())
        return node

    def parse_and(self):
        node = self.parse_not()
        while self.peek()[1] == "&&":
            self.next()
            node = ("and", node, self.parse_not())
        return node

    def parse_not(self):
        if self.peek()[1] == "!":
            self.next()
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_atom()
        if self.peek()[1] in ("==", "!=", "<=", ">=", "<", ">"):
            op = self.next()[1]
            right = self.parse_atom()
            return ("cmp", op, left, right)
        return left

    def parse_atom(self):
        kind, tok = self.next()
        if tok == "(":
            node = self.parse_or()
            self.expect(")")
            return node
        if kind == "string":
            return ("lit", tok[1:-1])
        if kind == "lit_backtick":
            inner = tok[1:-1]
            try:
                return ("lit", int(inner))
            except ValueError:
                try:
                    return ("lit", float(inner))
                except ValueError:
                    return ("lit", inner.strip('"'))
        if kind == "number":
            return ("lit", float(tok) if "." in tok else int(tok))
        if kind == "ident":
            if self.peek()[1] == "(":
                self.next()
                args = []
                if self.peek()[1] != ")":
                    args.append(self.parse_or())
                    while self.peek()[1] == ",":
                        self.next()
                        args.append(self.parse_or())
                self.expect(")")
                return ("call", tok, args)
            return ("path", tok)
        raise ValueError(f"unexpected token {tok!r}")


def _lookup(data: Any, path: str) -> Any:
    from pathway_tpu.internals.json import Json

    if isinstance(data, Json):
        data = data.value
    cur = data
    for part in path.split("."):
        if cur is None:
            return None
        if isinstance(cur, Json):
            cur = cur.value
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
    if isinstance(cur, Json):
        cur = cur.value
    return cur


def _eval(node, data) -> Any:
    kind = node[0]
    if kind == "lit":
        return node[1]
    if kind == "path":
        return _lookup(data, node[1])
    if kind == "and":
        return bool(_eval(node[1], data)) and bool(_eval(node[2], data))
    if kind == "or":
        return bool(_eval(node[1], data)) or bool(_eval(node[2], data))
    if kind == "not":
        return not bool(_eval(node[1], data))
    if kind == "cmp":
        _, op, l, r = node
        lv, rv = _eval(l, data), _eval(r, data)
        try:
            if op == "==":
                return lv == rv
            if op == "!=":
                return lv != rv
            if lv is None or rv is None:
                return False
            if op == "<":
                return lv < rv
            if op == "<=":
                return lv <= rv
            if op == ">":
                return lv > rv
            if op == ">=":
                return lv >= rv
        except TypeError:
            return False
    if kind == "call":
        _, name, args = node
        vals = [_eval(a, data) for a in args]
        if name == "contains":
            hay, needle = vals
            if hay is None:
                return False
            return needle in hay
        if name == "globmatch":
            pattern, path = vals
            if path is None:
                return False
            return _globmatch(str(pattern), str(path))
        if name == "starts_with":
            s, prefix = vals
            return s is not None and str(s).startswith(str(prefix))
        if name == "ends_with":
            s, suffix = vals
            return s is not None and str(s).endswith(str(suffix))
        if name == "length":
            return len(vals[0]) if vals[0] is not None else 0
        raise ValueError(f"unknown filter function {name!r}")
    raise ValueError(f"bad node {node!r}")


def _globmatch(pattern: str, path: str) -> bool:
    # '**' crosses directory separators, '*' does not
    regex = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                regex.append(".*")
                i += 2
                if i < len(pattern) and pattern[i] == "/":
                    i += 1
                continue
            regex.append("[^/]*")
        elif c == "?":
            regex.append("[^/]")
        else:
            regex.append(re.escape(c))
        i += 1
    return re.fullmatch("".join(regex), path) is not None


_cache: dict[str, Any] = {}


def compile_filter(expr: str):
    node = _cache.get(expr)
    if node is None:
        node = _Parser(_tokenize(expr)).parse()
        _cache[expr] = node
    return node


def evaluate_filter(expr: str, data: Any) -> bool:
    if not expr:
        return True
    try:
        return bool(_eval(compile_filter(expr), data))
    except Exception:
        return False
