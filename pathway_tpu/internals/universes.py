"""pw.universes — universe promises
(reference: python/pathway/internals/universes.py)."""

from __future__ import annotations


def promise_are_pairwise_disjoint(*tables) -> None:
    return None


def promise_are_equal(*tables) -> None:
    for t in tables[1:]:
        tables[0].promise_universes_are_equal(t)


def promise_is_subset_of(table, other) -> None:
    table.promise_universe_is_subset_of(other)
