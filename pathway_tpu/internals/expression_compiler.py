"""Expression AST → batched evaluators.

The compile-side counterpart of the reference's per-context
ExpressionEvaluators (python/pathway/internals/graph_runner/
expression_evaluator.py) and the engine interpreter
(src/engine/expression.rs) — except evaluation is *batched*: each compiled
node maps a whole delta's column to a result column. Sync UDFs run once per
batch; async UDFs gather the whole batch on one event loop (the reference
takes the GIL once per batch and calls Python per row —
dataflow.rs:1258-1318; we never go per-row across a runtime boundary).

Numeric columns use numpy fast paths; object columns fall back to per-row
Python with ERROR-sentinel propagation per cell.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import operations as ops
from pathway_tpu.internals.error import ERROR, global_error_log
from pathway_tpu.internals.keys import hash_values

Batch = list  # column of values, len == n rows


class CompileContext:
    """Maps column references to tuple positions in the engine row."""

    def __init__(self):
        self.col_pos: dict[tuple[int, str], int] = {}
        self.id_tables: set[int] = set()
        self.id_pos: dict[int, int] = {}

    def add_table(self, table, offset: int) -> int:
        """Register `table`'s columns at `offset`; returns next free offset."""
        names = table._column_names()
        for i, name in enumerate(names):
            self.col_pos.setdefault((id(table), name), offset + i)
        self.id_tables.add(id(table))
        return offset + len(names)

    def alias(self, table, target) -> None:
        """Make references to `table` resolve like references to `target`."""
        for (tid, name), pos in list(self.col_pos.items()):
            if tid == id(target):
                self.col_pos.setdefault((id(table), name), pos)
        if id(target) in self.id_tables:
            self.id_tables.add(id(table))

    def position(self, ref: ex.ColumnReference) -> int:
        key = (id(ref.table), ref.name)
        if key not in self.col_pos:
            raise KeyError(
                f"column {ref.name!r} of table {ref.table!r} is not part of "
                "this context (did you mean pw.this, or join the tables first?)"
            )
        return self.col_pos[key]


class _AsyncLoop:
    """Shared background event loop for async UDF batches
    (reference: internals/graph_runner/async_utils.py)."""

    _instance = None

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="pathway-tpu-async-udf")
        self.thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "_AsyncLoop":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def gather(self, coros: list) -> list:
        async def _g():
            return await asyncio.gather(*coros, return_exceptions=True)

        fut = asyncio.run_coroutine_threadsafe(_g(), self.loop)
        return fut.result()


def run_coro_batch(coros: list) -> list:
    results = _AsyncLoop.get().gather(coros)
    out = []
    for r in results:
        if isinstance(r, Exception):
            global_error_log().log(f"async UDF failed: {r!r}")
            out.append(ERROR)
        else:
            out.append(r)
    return out


class ExpressionCompiler:
    def __init__(self, ctx: CompileContext):
        self.ctx = ctx
        self.has_non_deterministic = False
        # set when a compiled expression dispatches accelerator work
        # (batch UDF with device=True): the hosting operator is marked
        # device_bound so the scheduler can pipeline it (device bridge)
        self.has_device = False
        # the fused auto-jit program compiled for the last
        # compile_program call, if any (internals/autojit.py)
        self.autojit = None

    # -- public -------------------------------------------------------------
    def compile(self, expr: ex.ColumnExpression) -> Callable[[list, list], Batch]:
        return self._compile(expr)

    def compile_program(self, exprs: list[ex.ColumnExpression]):
        """Compile many output expressions into fn(keys, rows) -> list[tuple].

        With auto-jit on (internals/autojit.py, PATHWAY_AUTO_JIT), output
        expressions whose trees are fusable traceable-UDF chains compile
        additionally into ONE vectorized dispatch; the per-expression
        interpreted fns stay as the fallback/verification path, so the
        fused tier can never change results — only skip per-row calls.
        """
        fns = []
        nondet_idx = set()
        for i, e in enumerate(exprs):
            outer = self.has_non_deterministic
            self.has_non_deterministic = False
            fns.append(self._compile(e))
            if self.has_non_deterministic:
                nondet_idx.add(i)
            self.has_non_deterministic = outer or self.has_non_deterministic
        fused: list = []
        try:
            from pathway_tpu.internals import autojit

            fused = autojit.fuse_program(exprs, self.ctx)
        except Exception:
            fused = []
        self.autojit = fused or None
        if fused and nondet_idx <= {i for g in fused for i in g.expr_idx}:
            # Every "non-deterministic" expression fused. Fusion only
            # admits UDFs the classifier proved to be straight-line
            # numeric code (no host calls, no RNG-bearing modules), so
            # they are deterministic in fact — the default
            # deterministic=False merely declares them UNVERIFIED. The
            # caching DeterministicMapOperator (per-row blake2b
            # fingerprints) exists to replay values for genuinely
            # non-deterministic fns; here it would cost ~5x the fused
            # dispatch itself, so the lowering may use the plain map:
            # recomputation at retraction time reproduces the same bytes.
            self.has_non_deterministic = False
        if not fused:
            def program(keys, rows):
                cols = [fn(keys, rows) for fn in fns]
                return list(zip(*cols)) if cols else [() for _ in keys]

            return program

        plan = [(grp, [fns[i] for i in grp.expr_idx]) for grp in fused]

        def program(keys, rows):
            cols: list = [None] * len(fns)
            for grp, fallbacks in plan:
                fcols = grp.dispatch(keys, rows, fallbacks)
                if fcols is not None:
                    for i, c in zip(grp.expr_idx, fcols):
                        cols[i] = c
            for i, fn in enumerate(fns):
                if cols[i] is None:
                    cols[i] = fn(keys, rows)
            return list(zip(*cols)) if cols else [() for _ in keys]

        return program

    def compile_predicate(self, expr: ex.ColumnExpression):
        fn = self._compile(expr)

        def pred(keys, rows):
            return [bool(v) and v is not ERROR for v in fn(keys, rows)]

        return pred

    def compile_key_fn(self, exprs: list[ex.ColumnExpression]):
        fns = [self._compile(e) for e in exprs]

        def key_fn(keys, rows):
            cols = [fn(keys, rows) for fn in fns]
            return [hash_values(*vals) for vals in zip(*cols)]

        return key_fn

    def compile_row(self, expr) -> Callable[[Any, tuple], Any]:
        """Per-row evaluator ``fn(key, row) -> value``.

        Plain column refs / id refs — the overwhelmingly common case for
        group keys, join keys and reducer arguments — compile to a tuple
        index instead of a batch-of-one trip through the columnar
        machinery (the engine's exchange and state operators evaluate
        these per row, so this is the dataflow hot path)."""
        if not isinstance(expr, ex.ColumnExpression):
            const = expr
            return lambda key, row: const
        if isinstance(expr, ex.IdExpression):  # subclasses ColumnReference
            return lambda key, row: key
        if isinstance(expr, ex.ColumnReference):
            pos = self.ctx.position(expr)
            return lambda key, row: row[pos]
        if isinstance(expr, ex.ConstExpression):
            const = expr._value
            return lambda key, row: const
        batch_fn = self._compile(expr)
        return lambda key, row: batch_fn([key], [row])[0]

    # -- dispatch -----------------------------------------------------------
    def _compile(self, expr) -> Callable[[list, list], Batch]:
        if not isinstance(expr, ex.ColumnExpression):
            expr = ex.ConstExpression(expr)
        method = getattr(self, f"_compile_{type(expr).__name__}", None)
        if method is None:
            raise NotImplementedError(f"cannot compile {type(expr).__name__}")
        return method(expr)

    # -- leaves -------------------------------------------------------------
    def _compile_ConstExpression(self, expr):
        v = expr._value

        def fn(keys, rows):
            return [v] * len(keys)

        return fn

    def _compile_IdExpression(self, expr):
        pos = self.ctx.id_pos.get(id(expr.table))
        if pos is not None:
            def fn(keys, rows):
                return [r[pos] for r in rows]
            return fn

        def fn(keys, rows):
            return list(keys)

        return fn

    def _compile_ColumnReference(self, expr):
        pos = self.ctx.position(expr)

        def fn(keys, rows):
            return [r[pos] for r in rows]

        return fn

    # -- operators ----------------------------------------------------------

    # vectorizable ops over non-optional numeric columns — elementwise
    # array callables (BINARY_OPS' == / != are whole-array scalar equality
    # for ndarrays, so they get explicit elementwise forms here).
    # Division-family ops and exponent are excluded (zero divisors raise
    # in python but produce inf/nan in numpy); int overflow guards below
    # keep python's bigint semantics.
    _NUMERIC_FAST_OPS = {
        "+": np.add, "-": np.subtract, "*": np.multiply,
        "<": np.less, "<=": np.less_equal,
        ">": np.greater, ">=": np.greater_equal,
        "==": np.equal, "!=": np.not_equal,
        # division family: numpy matches python elementwise (floor toward
        # -inf, % sign follows divisor, / correctly rounded) EXCEPT for a
        # zero divisor (python raises → per-cell ERROR; numpy warns and
        # emits 0/inf/nan) — any zero in the divisor column falls back
        "//": np.floor_divide, "%": np.mod, "/": np.true_divide,
    }
    _INT_SAFE = 1 << 62
    _FLOAT_EXACT = float(1 << 53)  # beyond this, int->float64 rounds

    @staticmethod
    def _numeric_column(vals, pure_float: bool):
        """np array for a fast path, or None to fall back. ``pure_float``
        rejects float-kind arrays built from mixed runtime values: a
        statically-FLOAT column may hold python ints (types_lca widening),
        and coercing them would round >2^53 magnitudes and change per-row
        result types where the op preserves them (negation, if_else
        selection, exact int-vs-float comparison)."""
        try:
            a = np.asarray(vals)
        except Exception:
            return None
        k = a.dtype.kind
        if k not in "if":
            return None  # ERROR/None/bool/bigint cells present
        if k == "f" and pure_float and not all(
                type(v) is float for v in vals):
            return None
        return a

    def _numeric_fast_eligible(self, expr) -> bool:
        from pathway_tpu.internals.type_inference import infer_dtype

        if expr._op not in self._NUMERIC_FAST_OPS:
            return False
        try:
            ld = infer_dtype(expr._left)
            rd = infer_dtype(expr._right)
        except Exception:
            return False
        for d in (ld, rd):
            if d != dt.unoptionalize(d):  # optional: None semantics
                return False
            if dt.unoptionalize(d) not in (dt.INT, dt.FLOAT):
                return False
        return True

    def _compile_BinaryExpression(self, expr):
        lf = self._compile(expr._left)
        rf = self._compile(expr._right)
        op = ops.BINARY_OPS[expr._op]
        opname = expr._op
        fast = self._numeric_fast_eligible(expr)

        def slow(lv, rv):
            out = []
            for a, b in zip(lv, rv):
                if a is ERROR or b is ERROR:
                    out.append(ERROR)
                elif a is None or b is None:
                    if opname == "==":
                        out.append(a is None and b is None)
                    elif opname == "!=":
                        out.append(not (a is None and b is None))
                    else:
                        out.append(None)
                else:
                    try:
                        out.append(op(a, b))
                    except Exception as e:
                        global_error_log().log(f"{opname} failed: {e!r}")
                        out.append(ERROR)
            return out

        if not fast:
            def fn(keys, rows):
                return slow(lf(keys, rows), rf(keys, rows))

            return fn

        int_safe = self._INT_SAFE
        float_exact = self._FLOAT_EXACT
        arith = opname in ("+", "-", "*", "//", "%", "/")
        divlike = opname in ("//", "%", "/")
        np_op = self._NUMERIC_FAST_OPS[opname]

        def magnitude(a) -> float:
            # NOT np.abs().max(): abs(INT64_MIN) wraps negative and would
            # slip past the guard
            return max(abs(float(a.max(initial=0))),
                       abs(float(a.min(initial=0))))

        def fn(keys, rows):
            lv = lf(keys, rows)
            rv = rf(keys, rows)
            if len(lv) < 8:  # array setup dominates tiny batches
                return slow(lv, rv)
            # comparisons are exact between int and float in python but
            # not after a float64 coercion, so they need pure columns
            la = self._numeric_column(lv, pure_float=not arith)
            ra = self._numeric_column(rv, pure_float=not arith)
            if la is None or ra is None:
                return slow(lv, rv)
            if divlike and bool((ra == 0).any()):
                # python raises (→ per-cell ERROR) where numpy warns
                return slow(lv, rv)
            lk, rk = la.dtype.kind, ra.dtype.kind
            if lk == "i" and rk == "i":
                if arith:
                    # keep python's arbitrary-precision ints:
                    # near-overflow magnitudes fall back (int64 wraps)
                    amax, bmax = magnitude(la), magnitude(ra)
                    if opname == "*":
                        if amax * bmax >= float(1 << 62):
                            return slow(lv, rv)
                    elif opname == "/":
                        # int/int → float: numpy converts operands to
                        # float64 FIRST, python divides exact ints — they
                        # differ beyond 2^53
                        if amax >= float_exact or bmax >= float_exact:
                            return slow(lv, rv)
                    elif amax >= int_safe or bmax >= int_safe:
                        return slow(lv, rv)
            elif lk != rk:
                # int-vs-float: numpy casts the int side to float64 first,
                # while python compares/combines exactly — ints beyond
                # 2^53 would round, so fall back
                ints = la if lk == "i" else ra
                if magnitude(ints) >= float_exact:
                    return slow(lv, rv)
            return np_op(la, ra).tolist()

        return fn

    def _compile_UnaryExpression(self, expr):
        af = self._compile(expr._arg)
        op = ops.UNARY_OPS[expr._op]
        fast_neg = False
        if expr._op == "-":
            from pathway_tpu.internals.type_inference import infer_dtype

            try:
                d = infer_dtype(expr._arg)
                fast_neg = (d == dt.unoptionalize(d)
                            and dt.unoptionalize(d) in (dt.INT, dt.FLOAT))
            except Exception:
                fast_neg = False

        def slow(vals):
            return [
                ERROR if v is ERROR else (None if v is None else op(v))
                for v in vals
            ]

        if not fast_neg:
            def fn(keys, rows):
                return slow(af(keys, rows))

            return fn

        numcol = self._numeric_column

        def fn(keys, rows):
            vals = af(keys, rows)
            if len(vals) < 8:
                return slow(vals)
            a = numcol(vals, pure_float=True)  # negation preserves types
            if a is None:
                return slow(vals)
            if a.dtype.kind == "i" and a.size and \
                    float(a.min(initial=0)) <= float(-(1 << 63)):
                return slow(vals)  # -INT64_MIN overflows int64
            return np.negative(a).tolist()

        return fn

    def _compile_IsNoneExpression(self, expr):
        af = self._compile(expr._arg)

        def fn(keys, rows):
            return [v is None for v in af(keys, rows)]

        return fn

    def _compile_IsNotNoneExpression(self, expr):
        af = self._compile(expr._arg)

        def fn(keys, rows):
            return [v is not None for v in af(keys, rows)]

        return fn

    def _compile_IfElseExpression(self, expr):
        cf = self._compile(expr._if)
        tf = self._compile(expr._then)
        ef = self._compile(expr._else)
        fast = False
        try:
            from pathway_tpu.internals.type_inference import infer_dtype

            td = infer_dtype(expr._then)
            ed = infer_dtype(expr._else)
            fast = (td == ed  # same static kind or the per-row types mix
                    and all(
                        d == dt.unoptionalize(d)
                        and dt.unoptionalize(d) in (dt.INT, dt.FLOAT)
                        for d in (td, ed)))
        except Exception:
            fast = False

        def slow(cond, tv, ev):
            return [
                ERROR if c is ERROR else (t if c else e)
                for c, t, e in zip(cond, tv, ev)
            ]

        numcol = self._numeric_column

        def fn(keys, rows):
            cond = cf(keys, rows)
            tv = tf(keys, rows)
            ev = ef(keys, rows)
            if not fast or len(cond) < 8:
                return slow(cond, tv, ev)
            try:
                ca = np.asarray(cond)
            except Exception:
                return slow(cond, tv, ev)
            if ca.dtype.kind != "b":  # ERROR cells in the condition
                return slow(cond, tv, ev)
            # selection preserves each value's own type, so both branches
            # must be pure columns of the SAME kind
            ta = numcol(tv, pure_float=True)
            ea = numcol(ev, pure_float=True)
            if ta is None or ea is None or ta.dtype.kind != ea.dtype.kind:
                return slow(cond, tv, ev)
            return np.where(ca, ta, ea).tolist()

        return fn

    def _compile_CoalesceExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]

        def fn(keys, rows):
            cols = [f(keys, rows) for f in fns]
            out = []
            for vals in zip(*cols):
                res = None
                for v in vals:
                    if v is not None and v is not ERROR:
                        res = v
                        break
                    if v is ERROR:
                        res = ERROR
                        break
                out.append(res)
            return out

        return fn

    def _compile_RequireExpression(self, expr):
        vf = self._compile(expr._val)
        fns = [self._compile(a) for a in expr._args]

        def fn(keys, rows):
            vals = vf(keys, rows)
            deps = [f(keys, rows) for f in fns]
            out = []
            for i, v in enumerate(vals):
                if any(d[i] is None for d in deps):
                    out.append(None)
                else:
                    out.append(v)
            return out

        return fn

    def _compile_CastExpression(self, expr):
        af = self._compile(expr._expr)
        target = expr._return_type

        def fn(keys, rows):
            out = []
            for v in af(keys, rows):
                try:
                    out.append(ops.cast_value(v, target))
                except Exception as e:
                    global_error_log().log(f"cast failed: {e!r}")
                    out.append(ERROR)
            return out

        return fn

    def _compile_ConvertExpression(self, expr):
        af = self._compile(expr._expr)
        target = expr._return_type
        unwrap = expr._unwrap

        def fn(keys, rows):
            out = []
            for v in af(keys, rows):
                try:
                    out.append(ops.convert_value(v, target, unwrap))
                except Exception as e:
                    global_error_log().log(f"convert failed: {e!r}")
                    out.append(ERROR)
            return out

        return fn

    def _compile_DeclareTypeExpression(self, expr):
        return self._compile(expr._expr)

    def _compile_UnwrapExpression(self, expr):
        af = self._compile(expr._expr)

        def fn(keys, rows):
            out = []
            for v in af(keys, rows):
                if v is None:
                    global_error_log().log("unwrap() got None")
                    out.append(ERROR)
                else:
                    out.append(v)
            return out

        return fn

    def _compile_FillErrorExpression(self, expr):
        af = self._compile(expr._expr)
        rf = self._compile(expr._replacement)

        def fn(keys, rows):
            vals = af(keys, rows)
            reps = rf(keys, rows)
            return [r if v is ERROR else v for v, r in zip(vals, reps)]

        return fn

    def _compile_MakeTupleExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]

        def fn(keys, rows):
            cols = [f(keys, rows) for f in fns]
            return [tuple(vals) for vals in zip(*cols)] if cols else [()] * len(keys)

        return fn

    def _compile_GetExpression(self, expr):
        of = self._compile(expr._obj)
        inf = self._compile(expr._index)
        df = self._compile(expr._default)
        check = expr._check_if_exists

        def fn(keys, rows):
            objs = of(keys, rows)
            idxs = inf(keys, rows)
            defs = df(keys, rows)
            out = []
            for o, i, d in zip(objs, idxs, defs):
                try:
                    out.append(ops.get_item(o, i, d, check))
                except Exception as e:
                    global_error_log().log(f"get_item failed: {e!r}")
                    out.append(ERROR)
            return out

        return fn

    def _compile_MethodCallExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]
        method = ops.METHODS[expr._method]
        kwargs = expr._kwargs

        def fn(keys, rows):
            cols = [f(keys, rows) for f in fns]
            out = []
            for vals in zip(*cols):
                if vals[0] is None:
                    out.append(None)
                    continue
                if any(v is ERROR for v in vals):
                    out.append(ERROR)
                    continue
                try:
                    out.append(method(*vals, **kwargs))
                except Exception as e:
                    global_error_log().log(f"{expr._method} failed: {e!r}")
                    out.append(ERROR)
            return out

        return fn

    def _compile_PointerExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]
        inst_fn = self._compile(expr._instance) if expr._instance is not None else None

        def fn(keys, rows):
            cols = [f(keys, rows) for f in fns]
            if inst_fn is not None:
                cols.append(inst_fn(keys, rows))
            return [hash_values(*vals) for vals in zip(*cols)]

        return fn

    def _compile_ApplyExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]
        kw_fns = {k: self._compile(v) for k, v in expr._kwargs.items()}
        f = expr._fn
        propagate_none = expr._propagate_none
        if not expr._deterministic:
            self.has_non_deterministic = True
        if getattr(expr, "_batch", False):
            if getattr(expr, "_device", False):
                self.has_device = True
            return self._compile_batch_apply(expr, fns, kw_fns)

        def fn(keys, rows):
            arg_cols = [g(keys, rows) for g in fns]
            kw_cols = {k: g(keys, rows) for k, g in kw_fns.items()}
            out = []
            for i in range(len(keys)):
                args = [c[i] for c in arg_cols]
                kws = {k: c[i] for k, c in kw_cols.items()}
                if any(a is ERROR for a in args) or any(
                        v is ERROR for v in kws.values()):
                    out.append(ERROR)
                    continue
                if propagate_none and (any(a is None for a in args) or any(
                        v is None for v in kws.values())):
                    out.append(None)
                    continue
                try:
                    out.append(f(*args, **kws))
                except Exception as e:
                    global_error_log().log(f"apply failed: {e!r}")
                    out.append(ERROR)
            return out

        return fn

    def _compile_batch_apply(self, expr, fns, kw_fns):
        """Columnar UDF dispatch: ``fn`` gets whole columns (lists aligned by
        row) and returns a list of results — one host→device round-trip per
        engine batch instead of per row. Rows with ERROR/None args are masked
        out before the call and spliced back after."""
        f = expr._fn
        propagate_none = expr._propagate_none
        max_bs = expr._max_batch_size

        def fn(keys, rows):
            arg_cols = [g(keys, rows) for g in fns]
            kw_cols = {k: g(keys, rows) for k, g in kw_fns.items()}
            n = len(keys)
            out: list = [None] * n
            live: list[int] = []
            for i in range(n):
                args_i = [c[i] for c in arg_cols]
                kws_i = [c[i] for c in kw_cols.values()]
                if any(a is ERROR for a in args_i) or any(
                        v is ERROR for v in kws_i):
                    out[i] = ERROR
                elif propagate_none and (any(a is None for a in args_i)
                                         or any(v is None for v in kws_i)):
                    out[i] = None
                else:
                    live.append(i)
            step = max_bs or len(live) or 1
            for lo in range(0, len(live), step):
                idx = live[lo:lo + step]
                args = [[c[i] for i in idx] for c in arg_cols]
                kws = {k: [c[i] for i in idx] for k, c in kw_cols.items()}
                try:
                    results = f(*args, **kws)
                    if len(results) != len(idx):
                        raise ValueError(
                            f"batch UDF returned {len(results)} results "
                            f"for {len(idx)} rows")
                    for i, r in zip(idx, results):
                        out[i] = r
                except Exception as e:
                    global_error_log().log(f"batch apply failed: {e!r}")
                    for i in idx:
                        out[i] = ERROR
            return out

        return fn

    def _compile_AsyncApplyExpression(self, expr):
        fns = [self._compile(a) for a in expr._args]
        kw_fns = {k: self._compile(v) for k, v in expr._kwargs.items()}
        f = expr._fn
        propagate_none = expr._propagate_none
        if not expr._deterministic:
            self.has_non_deterministic = True

        def fn(keys, rows):
            arg_cols = [g(keys, rows) for g in fns]
            kw_cols = {k: g(keys, rows) for k, g in kw_fns.items()}
            coros = []
            slots = []  # (index, precomputed | None)
            for i in range(len(keys)):
                args = [c[i] for c in arg_cols]
                kws = {k: c[i] for k, c in kw_cols.items()}
                if any(a is ERROR for a in args) or any(
                        v is ERROR for v in kws.values()):
                    slots.append((i, ERROR))
                elif propagate_none and (any(a is None for a in args) or any(
                        v is None for v in kws.values())):
                    slots.append((i, None))
                else:
                    slots.append((i, _PENDING))
                    coros.append(f(*args, **kws))
            results = run_coro_batch(coros) if coros else []
            out: list = [None] * len(keys)
            it = iter(results)
            for i, pre in slots:
                out[i] = next(it) if pre is _PENDING else pre
            return out

        return fn

    _compile_FullyAsyncApplyExpression = _compile_AsyncApplyExpression

    def _compile_ReducerExpression(self, expr):
        raise TypeError(
            f"reducer {expr._name!r} used outside groupby().reduce()"
        )


class _Pending:
    pass


_PENDING = _Pending()


def compile_map_program(exprs, ctx: CompileContext):
    comp = ExpressionCompiler(ctx)
    program = comp.compile_program(list(exprs))
    # carried as function attributes so the lowering can mark the hosting
    # MapOperator device_bound without changing every call site. An
    # auto-jit fused program joins the device leg exactly like an explicit
    # device=True batch UDF: its dispatches belong on the bridge worker so
    # the host thread can start the next tick's host-side work.
    program.autojit = comp.autojit
    program.device_bound = comp.has_device or comp.autojit is not None
    return program, comp.has_non_deterministic
