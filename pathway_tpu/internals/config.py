"""Runtime config from PATHWAY_* env vars
(reference: python/pathway/internals/config.py + src/engine/dataflow/config.rs).

Worker topology maps to the TPU mesh instead of timely threads/processes:
``PATHWAY_THREADS`` ≈ host-side ingest/worker threads,
``PATHWAY_PROCESSES``/``PATHWAY_PROCESS_ID`` ≈ multi-host topology. There is
deliberately no 8-worker license cap (reference caps at MAX_WORKERS=8,
config.rs:7-11; we don't) and no license server phone-home (license.rs:11).
"""

from __future__ import annotations

import os
from dataclasses import dataclass


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class PathwayConfig:
    license_key: str | None = None
    monitoring_server: str | None = None
    ignore_asserts: bool = False

    @property
    def threads(self) -> int:
        return _env_int("PATHWAY_THREADS", 1)

    @property
    def processes(self) -> int:
        return _env_int("PATHWAY_PROCESSES", 1)

    @property
    def process_id(self) -> int:
        return _env_int("PATHWAY_PROCESS_ID", 0)

    @property
    def first_port(self) -> int:
        return _env_int("PATHWAY_FIRST_PORT", 10000)

    @property
    def monitoring_http_port(self) -> int:
        return _env_int("PATHWAY_MONITORING_HTTP_PORT", 20000) + self.process_id

    @property
    def persistent_storage(self) -> str | None:
        return os.environ.get("PATHWAY_PERSISTENT_STORAGE")

    @property
    def run_id(self) -> str:
        return os.environ.get("PATHWAY_RUN_ID", "")

    @property
    def total_workers(self) -> int:
        return self.threads * self.processes


pathway_config = PathwayConfig()


def get_pathway_config() -> PathwayConfig:
    return pathway_config


def set_license_key(key: str | None) -> None:
    """Accepted for API compatibility; all features are always enabled."""
    pathway_config.license_key = key
