"""Hand-rolled SQL front-end for ``pw.sql``.

The reference compiles a sqlglot AST to Table ops
(python/pathway/internals/sql.py:63-726). sqlglot is not in this image, so
this module provides its own tokenizer + recursive-descent parser for the
same subset — SELECT / WHERE / GROUP BY / HAVING / JOIN (inner, left,
right, outer, cross) / UNION [ALL] / INTERSECT / WITH / DISTINCT — and
compiles it to the same Table-DSL calls the reference emits (select,
filter, groupby+reduce, join, concat_reindex).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any

from pathway_tpu.internals import expression as ex
from pathway_tpu.internals import reducers_frontend as reducers
from pathway_tpu.internals.table import Table

# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    \s+
  | (?P<string>'(?:[^']|'')*')
  | (?P<number>\d+\.\d*|\.\d+|\d+)
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*|"[^"]+")
  | (?P<op><>|!=|<=|>=|\|\||[=<>+\-*/%(),.])
    """,
    re.VERBOSE,
)

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "AS", "JOIN",
    "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "CROSS", "ON", "AND", "OR",
    "NOT", "NULL", "TRUE", "FALSE", "IN", "IS", "BETWEEN", "LIKE", "CASE",
    "WHEN", "THEN", "ELSE", "END", "UNION", "ALL", "INTERSECT", "WITH",
    "DISTINCT",
}


@dataclass
class Token:
    kind: str  # 'kw' | 'ident' | 'number' | 'string' | 'op' | 'eof'
    value: str


def tokenize(query: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    while pos < len(query):
        m = _TOKEN_RE.match(query, pos)
        if m is None:
            raise ValueError(f"SQL syntax error at: {query[pos:pos + 20]!r}")
        pos = m.end()
        if m.lastgroup is None:
            continue
        text = m.group(m.lastgroup)
        if m.lastgroup == "string":
            tokens.append(Token("string", text[1:-1].replace("''", "'")))
        elif m.lastgroup == "number":
            tokens.append(Token("number", text))
        elif m.lastgroup == "ident":
            if text.startswith('"'):
                tokens.append(Token("ident", text[1:-1]))
            elif text.upper() in _KEYWORDS:
                tokens.append(Token("kw", text.upper()))
            else:
                tokens.append(Token("ident", text))
        else:
            tokens.append(Token("op", text))
    tokens.append(Token("eof", ""))
    return tokens


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class TableRef:
    name: str | None = None
    subquery: Any = None  # SelectStmt | compound tuple
    alias: str | None = None


@dataclass
class JoinClause:
    kind: str  # inner | left | right | outer | cross
    table: TableRef = None
    on: Any = None


@dataclass
class SelectStmt:
    items: list = field(default_factory=list)  # (expr, alias|None) | ('*',)
    from_table: TableRef | None = None
    joins: list = field(default_factory=list)
    where: Any = None
    group_by: list = field(default_factory=list)
    having: Any = None
    distinct: bool = False


_AGG_FUNCS = {"count", "sum", "avg", "min", "max"}


class Parser:
    def __init__(self, tokens: list[Token]):
        self.tokens = tokens
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def accept(self, kind: str, value: str | None = None) -> Token | None:
        tok = self.peek()
        if tok.kind == kind and (value is None or tok.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: str | None = None) -> Token:
        tok = self.accept(kind, value)
        if tok is None:
            got = self.peek()
            raise ValueError(
                f"SQL parse error: expected {value or kind}, got "
                f"{got.value or got.kind!r}")
        return tok

    # -- statement ---------------------------------------------------------
    def parse(self):
        ctes = {}
        if self.accept("kw", "WITH"):
            while True:
                name = self.expect("ident").value
                self.expect("kw", "AS")
                self.expect("op", "(")
                ctes[name] = self.parse_compound()
                self.expect("op", ")")
                if not self.accept("op", ","):
                    break
        stmt = self.parse_compound()
        self.expect("eof")
        return ctes, stmt

    def parse_compound(self):
        left = self.parse_select()
        while True:
            if self.accept("kw", "UNION"):
                all_flag = self.accept("kw", "ALL") is not None
                right = self.parse_select()
                left = ("union", all_flag, left, right)
            elif self.accept("kw", "INTERSECT"):
                right = self.parse_select()
                left = ("intersect", left, right)
            else:
                return left

    def parse_select(self) -> SelectStmt:
        self.expect("kw", "SELECT")
        stmt = SelectStmt()
        stmt.distinct = self.accept("kw", "DISTINCT") is not None
        while True:
            if self.accept("op", "*"):
                stmt.items.append(("*",))
            else:
                expr = self.parse_expr()
                alias = None
                if self.accept("kw", "AS"):
                    alias = self.expect("ident").value
                elif self.peek().kind == "ident":
                    alias = self.next().value
                stmt.items.append((expr, alias))
            if not self.accept("op", ","):
                break
        if self.accept("kw", "FROM"):
            stmt.from_table = self.parse_table_ref()
            while True:
                kind = None
                if self.accept("kw", "CROSS"):
                    kind = "cross"
                elif self.accept("kw", "INNER"):
                    kind = "inner"
                elif self.accept("kw", "LEFT"):
                    self.accept("kw", "OUTER")
                    kind = "left"
                elif self.accept("kw", "RIGHT"):
                    self.accept("kw", "OUTER")
                    kind = "right"
                elif self.accept("kw", "FULL"):
                    self.accept("kw", "OUTER")
                    kind = "outer"
                elif self.peek().kind == "kw" and self.peek().value == "JOIN":
                    kind = "inner"
                if kind is None:
                    break
                self.expect("kw", "JOIN")
                ref = self.parse_table_ref()
                on = None
                if kind != "cross":
                    self.expect("kw", "ON")
                    on = self.parse_expr()
                stmt.joins.append(JoinClause(kind, ref, on))
        if self.accept("kw", "WHERE"):
            stmt.where = self.parse_expr()
        if self.accept("kw", "GROUP"):
            self.expect("kw", "BY")
            while True:
                stmt.group_by.append(self.parse_expr())
                if not self.accept("op", ","):
                    break
        if self.accept("kw", "HAVING"):
            stmt.having = self.parse_expr()
        return stmt

    def parse_table_ref(self) -> TableRef:
        if self.accept("op", "("):
            sub = self.parse_compound()
            self.expect("op", ")")
            ref = TableRef(subquery=sub)
        else:
            ref = TableRef(name=self.expect("ident").value)
        if self.accept("kw", "AS"):
            ref.alias = self.expect("ident").value
        elif self.peek().kind == "ident":
            ref.alias = self.next().value
        return ref

    # -- expressions (precedence climbing) ---------------------------------
    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        left = self.parse_and()
        while self.accept("kw", "OR"):
            left = ("bin", "or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.accept("kw", "AND"):
            left = ("bin", "and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.accept("kw", "NOT"):
            return ("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self):
        left = self.parse_addsub()
        tok = self.peek()
        if tok.kind == "op" and tok.value in ("=", "<>", "!=", "<", "<=",
                                              ">", ">="):
            op = self.next().value
            op = {"=": "==", "<>": "!="}.get(op, op)
            return ("bin", op, left, self.parse_addsub())
        if tok.kind == "kw" and tok.value == "IS":
            self.next()
            neg = self.accept("kw", "NOT") is not None
            self.expect("kw", "NULL")
            return ("isnull", left, neg)
        neg = False
        if tok.kind == "kw" and tok.value == "NOT":
            nxt = self.tokens[self.i + 1]
            if nxt.kind == "kw" and nxt.value in ("IN", "BETWEEN", "LIKE"):
                self.next()
                neg = True
                tok = self.peek()
        if tok.kind == "kw" and tok.value == "IN":
            self.next()
            self.expect("op", "(")
            vals = [self.parse_expr()]
            while self.accept("op", ","):
                vals.append(self.parse_expr())
            self.expect("op", ")")
            return ("in", left, vals, neg)
        if tok.kind == "kw" and tok.value == "BETWEEN":
            self.next()
            lo = self.parse_addsub()
            self.expect("kw", "AND")
            hi = self.parse_addsub()
            return ("between", left, lo, hi, neg)
        if tok.kind == "kw" and tok.value == "LIKE":
            self.next()
            pattern = self.expect("string").value
            return ("like", left, pattern, neg)
        return left

    def parse_addsub(self):
        left = self.parse_muldiv()
        while True:
            tok = self.peek()
            if tok.kind == "op" and tok.value in ("+", "-", "||"):
                self.next()
                left = ("bin", tok.value, left, self.parse_muldiv())
            else:
                return left

    def parse_muldiv(self):
        left = self.parse_unary()
        while True:
            tok = self.peek()
            if tok.kind == "op" and tok.value in ("*", "/", "%"):
                self.next()
                left = ("bin", tok.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self):
        if self.accept("op", "-"):
            return ("neg", self.parse_unary())
        if self.accept("op", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self):
        tok = self.next()
        if tok.kind == "number":
            return ("lit", float(tok.value) if "." in tok.value
                    else int(tok.value))
        if tok.kind == "string":
            return ("lit", tok.value)
        if tok.kind == "kw":
            if tok.value == "NULL":
                return ("lit", None)
            if tok.value == "TRUE":
                return ("lit", True)
            if tok.value == "FALSE":
                return ("lit", False)
            if tok.value == "CASE":
                whens = []
                while self.accept("kw", "WHEN"):
                    cond = self.parse_expr()
                    self.expect("kw", "THEN")
                    whens.append((cond, self.parse_expr()))
                default = ("lit", None)
                if self.accept("kw", "ELSE"):
                    default = self.parse_expr()
                self.expect("kw", "END")
                return ("case", whens, default)
            raise ValueError(f"SQL parse error: unexpected {tok.value}")
        if tok.kind == "op" and tok.value == "(":
            inner = self.parse_expr()
            self.expect("op", ")")
            return inner
        if tok.kind == "ident":
            # function call
            if self.accept("op", "("):
                name = tok.value.lower()
                if self.accept("op", "*"):
                    self.expect("op", ")")
                    return ("func", name, [], True)
                args = []
                if not self.accept("op", ")"):
                    args.append(self.parse_expr())
                    while self.accept("op", ","):
                        args.append(self.parse_expr())
                    self.expect("op", ")")
                return ("func", name, args, False)
            # qualified column tab.col
            if self.accept("op", "."):
                col = self.expect("ident").value
                return ("col", tok.value, col)
            return ("col", None, tok.value)
        raise ValueError(f"SQL parse error: unexpected {tok.value!r}")


# ---------------------------------------------------------------------------
# compiler: AST → Table ops
# ---------------------------------------------------------------------------

class Scope:
    """alias → (Table column-name mapping into the current flat table)."""

    def __init__(self):
        self.entries: list[tuple[str | None, dict[str, str]]] = []
        self.table: Table | None = None

    def resolve(self, alias: str | None, name: str) -> ex.ColumnReference:
        if alias is not None:
            for a, cols in self.entries:
                if a == alias:
                    if name not in cols:
                        raise KeyError(
                            f"no column {name!r} in table {alias!r}")
                    return self.table[cols[name]]
            raise KeyError(f"unknown table alias {alias!r}")
        hits = [cols[name] for _a, cols in self.entries if name in cols]
        if not hits:
            raise KeyError(f"unknown column {name!r}")
        if len(set(hits)) > 1:
            raise ValueError(f"ambiguous column {name!r}")
        return self.table[hits[0]]

    def all_columns(self) -> list[tuple[str, str]]:
        """(output name, flat name) for SELECT *."""
        out = []
        seen = set()
        for _a, cols in self.entries:
            for name, flat in cols.items():
                if name in seen:
                    raise ValueError(
                        f"SELECT * with duplicate column {name!r}; "
                        "qualify the select list instead")
                seen.add(name)
                out.append((name, flat))
        return out


def _like_matcher(pattern: str):
    regex = re.compile(
        "^" + re.escape(pattern).replace("%", ".*").replace("_", ".") + "$",
        re.DOTALL)

    def match(value):
        return value is not None and regex.match(str(value)) is not None

    return match


class Compiler:
    def __init__(self, env: dict[str, Table]):
        self.env = env

    def lookup_table(self, name: str) -> Table:
        if name in self.env:
            return self.env[name]
        for k, v in self.env.items():
            if k.lower() == name.lower():
                return v
        raise KeyError(f"unknown table {name!r} in SQL query")

    # -- expression --------------------------------------------------------
    def expr(self, node, scope: Scope):
        kind = node[0]
        if kind == "lit":
            return ex.wrap_arg(node[1])
        if kind == "col":
            return scope.resolve(node[1], node[2])
        if kind == "bin":
            op, l, r = node[1], self.expr(node[2], scope), self.expr(node[3], scope)
            if op == "and":
                return l & r
            if op == "or":
                return l | r
            if op == "||":
                return ex.apply(lambda a, b: (str(a) if a is not None else "")
                                + (str(b) if b is not None else ""), l, r)
            import operator as _op

            table = {"+": _op.add, "-": _op.sub, "*": _op.mul,
                     "/": _op.truediv, "%": _op.mod, "==": _op.eq,
                     "!=": _op.ne, "<": _op.lt, "<=": _op.le, ">": _op.gt,
                     ">=": _op.ge}
            return table[op](l, r)
        if kind == "not":
            return ~self.expr(node[1], scope)
        if kind == "neg":
            return -self.expr(node[1], scope)
        if kind == "isnull":
            e = self.expr(node[1], scope)
            res = ex.IsNoneExpression(e)
            return ~res if node[2] else res
        if kind == "in":
            e = self.expr(node[1], scope)
            folded = None
            for v in node[2]:
                term = e == self.expr(v, scope)
                folded = term if folded is None else folded | term
            return ~folded if node[3] else folded
        if kind == "between":
            e = self.expr(node[1], scope)
            lo, hi = self.expr(node[2], scope), self.expr(node[3], scope)
            res = (e >= lo) & (e <= hi)
            return ~res if node[4] else res
        if kind == "like":
            e = self.expr(node[1], scope)
            res = ex.apply(_like_matcher(node[2]), e)
            return ~res if node[3] else res
        if kind == "case":
            whens, default = node[1], node[2]
            out = self.expr(default, scope)
            for cond, val in reversed(whens):
                out = ex.if_else(self.expr(cond, scope),
                                 self.expr(val, scope), out)
            return out
        if kind == "func":
            return self.func(node, scope)
        raise ValueError(f"cannot compile SQL expression {node!r}")

    def func(self, node, scope: Scope):
        name, args, star = node[1], node[2], node[3]
        if name in _AGG_FUNCS:
            if name == "count":
                return reducers.count()
            [arg] = args
            return getattr(reducers, name)(self.expr(arg, scope))
        compiled = [self.expr(a, scope) for a in args]
        if name == "coalesce":
            return ex.coalesce(*compiled)
        if name == "nullif":
            a, b = compiled
            return ex.if_else(a == b, ex.wrap_arg(None), a)
        simple = {
            "abs": abs,
            "lower": lambda s: s.lower() if s is not None else None,
            "upper": lambda s: s.upper() if s is not None else None,
            "length": lambda s: len(s) if s is not None else None,
            "round": lambda x, *d: round(x, *[int(v) for v in d])
            if x is not None else None,
        }
        if name in simple:
            return ex.apply(simple[name], *compiled)
        raise ValueError(f"unsupported SQL function {name!r}")

    def _has_aggregate(self, node) -> bool:
        if not isinstance(node, tuple):
            return False
        if node[0] == "func" and node[1] in _AGG_FUNCS:
            return True
        for child in node:
            if isinstance(child, tuple) and self._has_aggregate(child):
                return True
            if isinstance(child, list) and any(
                    self._has_aggregate(x) for x in child):
                return True
        return False

    # -- FROM / JOIN -------------------------------------------------------
    def table_for_ref(self, ref: TableRef) -> tuple[Table, str | None]:
        if ref.subquery is not None:
            return self.compile_compound(ref.subquery), ref.alias
        t = self.lookup_table(ref.name)
        return t, ref.alias or ref.name

    def build_scope(self, stmt: SelectStmt) -> Scope:
        scope = Scope()
        base, alias = self.table_for_ref(stmt.from_table)
        scope.table = base
        scope.entries.append((alias, {c: c for c in base.column_names()}))

        for join in stmt.joins:
            right, ralias = self.table_for_ref(join.table)
            flat_names = {c for _a, cols in scope.entries
                          for c in cols.values()}
            rmap = {}
            for c in right.column_names():
                flat = c if c not in flat_names else f"{ralias}__{c}"
                i = 1
                while flat in flat_names:
                    flat = f"{ralias}__{c}_{i}"
                    i += 1
                rmap[c] = flat
                flat_names.add(flat)

            rscope = Scope()
            rscope.table = right
            rscope.entries.append((ralias, {c: c for c in right.column_names()}))

            conds, post = self.split_on(join.on, scope, rscope)
            how = join.kind
            if join.kind == "cross":
                # every row matches: constant join key on both sides
                conds = [ex.wrap_arg(0) == ex.wrap_arg(0)]
                how = "inner"
            if post is not None and join.kind != "inner":
                raise ValueError(
                    "non-equality ON conditions are only supported for "
                    "INNER JOIN")
            jr = scope.table.join(right, *conds, how=how)
            kwargs = {}
            for _a, cols in scope.entries:
                for name, flat in cols.items():
                    kwargs[flat] = scope.table[flat]
            for c, flat in rmap.items():
                kwargs[flat] = right[c]
            flat_table = jr.select(**kwargs)

            new = Scope()
            new.table = flat_table
            new.entries = [(a, dict(cols)) for a, cols in scope.entries]
            new.entries.append((ralias, rmap))
            scope = new
            if post is not None:
                # re-resolve the residual condition against the flat table
                scope.table = scope.table.filter(self.expr(post, scope))
        return scope

    def split_on(self, on, lscope: Scope, rscope: Scope):
        """Split an ON conjunction into equality pairs usable as join
        conditions (left_expr == right_expr) and a residual predicate."""
        if on is None:
            return [], None
        conjuncts = []

        def flatten(n):
            if isinstance(n, tuple) and n[0] == "bin" and n[1] == "and":
                flatten(n[2])
                flatten(n[3])
            else:
                conjuncts.append(n)

        flatten(on)
        conds, residual = [], []
        for c in conjuncts:
            if isinstance(c, tuple) and c[0] == "bin" and c[1] == "==":
                sides = []
                ok = True
                for sub in (c[2], c[3]):
                    try:
                        sides.append(self.expr(sub, lscope))
                        side_of = "l"
                    except (KeyError, ValueError):
                        try:
                            sides.append(self.expr(sub, rscope))
                            side_of = "r"
                        except (KeyError, ValueError):
                            ok = False
                            break
                    sides[-1] = (side_of, sides[-1])
                if ok and len(sides) == 2:
                    tags = {sides[0][0], sides[1][0]}
                    if tags == {"l", "r"}:
                        l = next(e for t, e in sides if t == "l")
                        r = next(e for t, e in sides if t == "r")
                        conds.append(l == r)
                        continue
            residual.append(c)
        post = None
        for c in residual:
            post = c if post is None else ("bin", "and", post, c)
        return conds, post

    # -- SELECT ------------------------------------------------------------
    def output_name(self, item, i: int) -> str:
        expr, alias = item
        if alias:
            return alias
        if isinstance(expr, tuple) and expr[0] == "col":
            return expr[2]
        if isinstance(expr, tuple) and expr[0] == "func":
            return expr[1]
        return f"col_{i}"

    def compile_select(self, stmt: SelectStmt) -> Table:
        scope = self.build_scope(stmt) if stmt.from_table is not None else None
        if scope is None:
            raise ValueError("SELECT without FROM is not supported")
        t = scope.table
        if stmt.where is not None:
            t = t.filter(self.expr(stmt.where, scope))
            scope.table = t

        has_agg = any(
            item[0] != "*" and self._has_aggregate(item[0])
            for item in stmt.items
        ) or (stmt.having is not None and self._has_aggregate(stmt.having))

        if stmt.group_by or has_agg:
            out = {}
            used = set()
            for i, item in enumerate(stmt.items):
                if item[0] == "*":
                    raise ValueError("SELECT * cannot be mixed with GROUP BY")
                name = self.output_name(item, i)
                if name in used:
                    raise ValueError(
                        f"duplicate output column {name!r} in SELECT — "
                        "disambiguate with AS")
                used.add(name)
                out[name] = self.expr(item[0], scope)
            by = [self.expr(g, scope) for g in stmt.group_by]
            if stmt.having is not None:
                out["__having__"] = self.expr(stmt.having, scope)
            if by:
                result = t.groupby(*by).reduce(**out)
            else:
                result = t.reduce(**out)
            if stmt.having is not None:
                result = result.filter(result["__having__"]).without(
                    "__having__")
        else:
            out = {}
            used = set()
            for i, item in enumerate(stmt.items):
                if item[0] == "*":
                    for name, flat in scope.all_columns():
                        if name in used:
                            raise ValueError(
                                f"duplicate output column {name!r} in "
                                "SELECT — disambiguate with AS")
                        out[name] = t[flat]
                        used.add(name)
                    continue
                name = self.output_name(item, i)
                if name in used:
                    raise ValueError(
                        f"duplicate output column {name!r} in SELECT — "
                        "disambiguate with AS")
                used.add(name)
                out[name] = self.expr(item[0], scope)
            result = t.select(**out)

        if stmt.distinct:
            result = _distinct(result)
        return result

    def compile_compound(self, node) -> Table:
        if isinstance(node, SelectStmt):
            return self.compile_select(node)
        if node[0] == "union":
            _tag, all_flag, l, r = node
            lt, rt = self.compile_compound(l), self.compile_compound(r)
            combined = lt.concat_reindex(rt)
            return combined if all_flag else _distinct(combined)
        if node[0] == "intersect":
            lt = _distinct(self.compile_compound(node[1]))
            rt = _distinct(self.compile_compound(node[2]))
            cols = lt.column_names()
            rcols = rt.column_names()
            conds = [lt[c] == rt[rc] for c, rc in zip(cols, rcols)]
            return lt.join(rt, *conds, how="inner").select(
                **{c: lt[c] for c in cols})
        raise ValueError(f"unknown compound node {node[0]!r}")


def _distinct(t: Table) -> Table:
    cols = t.column_names()
    return t.groupby(*[t[c] for c in cols]).reduce(**{c: t[c] for c in cols})


def compile_sql(query: str, tables: dict[str, Table]) -> Table:
    ctes, stmt = Parser(tokenize(query)).parse()
    env = dict(tables)
    compiler = Compiler(env)
    for name, sub in ctes.items():
        env[name] = compiler.compile_compound(sub)
    return compiler.compile_compound(stmt)
