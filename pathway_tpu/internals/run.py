"""pw.run — execute the collected pipeline
(reference: python/pathway/internals/run.py:12-52)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


def run(*, debug: bool = False, monitoring_level=None, with_http_server: bool = False,
        default_logging: bool = True, persistence_config=None,
        runtime_typechecking: bool | None = None, terminate_on_error: bool = True,
        telemetry_config=None, static_check: str | None = None,
        connector_policy=None, watchdog=None, trace_path: str | None = None,
        replica_of: str | None = None, qos=None, **kwargs) -> Any:
    """Build the engine graph from all registered outputs and run it.

    Static-only graphs run in batch mode to completion; graphs with streaming
    sources enter the realtime microbatch loop (pathway_tpu/engine/streaming.py)
    until all sources finish or the process is stopped.

    ``trace_path`` (or ``PATHWAY_TRACE_PATH``) turns on the flight
    recorder (engine/flight_recorder.py) and writes the run's span buffer
    as Chrome trace-event JSON — host and device legs on separate tracks,
    per-operator spans with user-frame attribution — loadable directly in
    Perfetto (README "Observability").

    ``connector_policy`` is the default :class:`pw.ConnectorPolicy`
    (retry/backoff/escalation) applied to streaming sources that did not
    pick their own; ``watchdog`` a :class:`pw.WatchdogConfig` tuning stall
    detection (engine/supervisor.py). With ``terminate_on_error=True`` a
    connector whose retries are exhausted stops the runtime and its
    exception re-raises from here; with ``False`` the failure lands in the
    global error log and the rest of the pipeline keeps serving.

    ``static_check`` runs the pre-execution analyzer
    (internals/static_check/) over the collected plan DAG first:
    ``"warn"`` logs every diagnostic, ``"error"`` additionally raises
    :class:`StaticCheckError` on error-severity findings, ``"off"`` (the
    default, also settable via ``PATHWAY_STATIC_CHECK``) skips analysis.
    ``PATHWAY_STATIC_CHECK_MESH`` (e.g. ``"4x2"``) arms the mesh-dependent
    sharding checks (PWT1xx) against that topology. The UDF-traceability
    classifications recorded on apply expressions (``_shard_class``) feed
    the auto-jit tier (internals/autojit.py): traceable/vmappable sync
    UDF chains compile into fused vectorized dispatches at graph lowering,
    byte-identical to the interpreted path, on by default and disabled
    with ``PATHWAY_AUTO_JIT=0`` (README "Auto-jit").

    ``qos`` (or ``PATHWAY_QOS=1``) arms the QoS control plane
    (engine/qos.py): per-tick device-time budgeting between query and
    ingest work steered by the SLO burn rate, bounded query admission
    with deadline-aware shedding (503 + ``Retry-After``), and
    cross-request coalescing accounting. ``True`` / a
    :class:`pw.QosConfig` enable it, ``False`` disables explicitly
    (the PWT013 waiver), ``None`` defers to the environment. QoS
    implies the flight recorder (the controller feeds on the request
    tracker; README "QoS & admission control").

    ``replica_of`` (or ``PATHWAY_REPLICA_OF``) runs this program as a
    snapshot-hydrated READ REPLICA of the primary whose persistence root
    it names (engine/replica.py): operator state restores from the newest
    valid snapshot generation, persisted feeds are tailed from the
    primary's WAL through a read-only driver, rest routes serve
    ``query_as_of_now`` at the replica's applied tick, and — when
    ``PATHWAY_ROUTER_CONTROL`` names a router (engine/router.py) — the
    process registers and heartbeats staleness/latency over the framed
    HMAC control channel (README "Replica fleet").
    """
    import os as _os

    from pathway_tpu.internals.config import get_pathway_config

    if replica_of is None:
        replica_of = _os.environ.get("PATHWAY_REPLICA_OF") or None
    if persistence_config is None and replica_of is None:
        persistence_config = _persistence_config_from_env()
    replica = None
    if replica_of is not None:
        from pathway_tpu.engine.replica import ReplicaTailer

        replica = ReplicaTailer(replica_of)
    _run_static_check(static_check, persistence_config, terminate_on_error,
                      connector_policy, qos=qos)

    cfg = get_pathway_config()
    cluster = None
    if cfg.processes > 1:
        # SPMD cluster: every process runs this same program and owns a
        # contiguous block of PATHWAY_THREADS logical workers; rows cross
        # processes at exchange boundaries over TCP (engine/multiproc.py;
        # reference: timely cluster, config.rs:62-120, cli spawn -n)
        from pathway_tpu.engine.multiproc import get_cluster

        cluster = get_cluster()
    from pathway_tpu.internals.telemetry import Config as TelemetryConfig
    from pathway_tpu.internals.telemetry import Telemetry

    if telemetry_config is None:
        telemetry_config = TelemetryConfig.create()
    telemetry = Telemetry(telemetry_config)

    runner = GraphRunner()
    with telemetry.span("pathway.graph.build"):
        for binder in G.output_binders:
            binder(runner)
    if persistence_config is not None:
        runner._persistence_config = persistence_config
    try:
        with telemetry.span("pathway.run",
                            run_id=telemetry_config.run_id or ""):
            if runner._stream_subjects:
                from pathway_tpu.engine.streaming import StreamingRuntime

                rt = StreamingRuntime(
                    runner, monitoring_level=monitoring_level,
                    with_http_server=with_http_server,
                    persistence_config=persistence_config,
                    terminate_on_error=terminate_on_error,
                    connector_policy=connector_policy, watchdog=watchdog,
                    cluster=cluster, trace_path=trace_path,
                    replica=replica, qos=qos)
                telemetry.register_scheduler_gauges(rt.scheduler,
                                                    runner.graph)
                if rt.recorder is not None:
                    # recorded spans also flow through the OTel provider
                    # when a real SDK pipeline is configured
                    rt.recorder.set_telemetry(telemetry)
                rt.run()
            else:
                if replica is not None:
                    raise ValueError(
                        "replica_of= requires a streaming pipeline (a "
                        "batch graph has no WAL to tail and nothing to "
                        "serve)")
                from pathway_tpu.engine.flight_recorder import FlightRecorder

                recorder = FlightRecorder.from_env(trace_path=trace_path)
                if recorder is not None:
                    recorder.set_telemetry(telemetry)
                runner.run_batch(cluster=cluster, recorder=recorder)
    finally:
        telemetry.shutdown()
    return runner


def run_all(**kwargs):
    return run(**kwargs)


def _run_static_check(mode: str | None, persistence_config,
                      terminate_on_error: bool | None = None,
                      connector_policy=None, qos=None) -> None:
    """Opt-in pre-execution analysis gate for pw.run."""
    import os

    if mode is None:
        mode = os.environ.get("PATHWAY_STATIC_CHECK", "off")
    if mode in ("off", "", None):
        return
    if mode not in ("warn", "error"):
        raise ValueError(
            f"static_check must be 'off', 'warn' or 'error', got {mode!r}")
    import logging

    from pathway_tpu.internals.static_check import (Severity, StaticCheckError,
                                                    analyze)

    # PWT013 arming (the run knows its own QoS decision — the analyzer's
    # tri-state: True/False are decisions, None defers to the env)
    qos_enabled: bool | None
    if qos is None:
        from pathway_tpu.engine.qos import qos_enabled_from_env

        qos_enabled = qos_enabled_from_env()
    else:
        qos_enabled = bool(qos)
    diagnostics = analyze(
        graph=G, persisted=persistence_config is not None,
        mesh=os.environ.get("PATHWAY_STATIC_CHECK_MESH") or None,
        terminate_on_error=terminate_on_error,
        connector_policy=connector_policy, qos_enabled=qos_enabled)
    if not diagnostics:
        return
    log = logging.getLogger("pathway_tpu.static_check")
    levels = {Severity.ERROR: logging.ERROR,
              Severity.WARNING: logging.WARNING,
              Severity.INFO: logging.INFO}
    # errors first, and each finding at its own severity so log-level
    # filters and warning-based alerting see what the analyzer meant
    for d in sorted(diagnostics, key=lambda d: levels[d.severity],
                    reverse=True):
        log.log(levels[d.severity], "%s", d)
    if mode == "error" and any(d.is_error for d in diagnostics):
        raise StaticCheckError(diagnostics)


def _persistence_config_from_env():
    """Record/replay wiring set by the CLI (cli.py spawn --record / replay):
    PATHWAY_REPLAY_STORAGE + PATHWAY_SNAPSHOT_ACCESS + PATHWAY_PERSISTENCE_MODE
    + PATHWAY_CONTINUE_AFTER_REPLAY (reference: cli.py:178-187, engine env)."""
    import os

    path = os.environ.get("PATHWAY_REPLAY_STORAGE") or os.environ.get(
        "PATHWAY_PERSISTENT_STORAGE")
    if not path:
        return None
    from pathway_tpu import persistence

    mode = os.environ.get("PATHWAY_PERSISTENCE_MODE", "persisting")
    cont = os.environ.get("PATHWAY_CONTINUE_AFTER_REPLAY", "")
    access = os.environ.get("PATHWAY_SNAPSHOT_ACCESS", "")
    continue_after_replay = cont.lower() in ("1", "true", "yes") or (
        access == "record")
    return persistence.Config(
        backend=persistence.Backend.filesystem(path),
        persistence_mode=mode,
        continue_after_replay=continue_after_replay,
    )
