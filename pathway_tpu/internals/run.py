"""pw.run — execute the collected pipeline
(reference: python/pathway/internals/run.py:12-52)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.parse_graph import G
from pathway_tpu.internals.runner import GraphRunner


def run(*, debug: bool = False, monitoring_level=None, with_http_server: bool = False,
        default_logging: bool = True, persistence_config=None,
        runtime_typechecking: bool | None = None, terminate_on_error: bool = True,
        **kwargs) -> Any:
    """Build the engine graph from all registered outputs and run it.

    Static-only graphs run in batch mode to completion; graphs with streaming
    sources enter the realtime microbatch loop (pathway_tpu/engine/streaming.py)
    until all sources finish or the process is stopped.
    """
    runner = GraphRunner()
    for binder in G.output_binders:
        binder(runner)
    if persistence_config is not None:
        runner._persistence_config = persistence_config
    if runner._stream_subjects:
        from pathway_tpu.engine.streaming import StreamingRuntime

        rt = StreamingRuntime(runner, monitoring_level=monitoring_level,
                              with_http_server=with_http_server,
                              persistence_config=persistence_config,
                              terminate_on_error=terminate_on_error)
        rt.run()
    else:
        runner.run_batch()
    return runner


def run_all(**kwargs):
    return run(**kwargs)
