"""Readable reprs for expressions
(reference: python/pathway/internals/expression_printer.py)."""

from __future__ import annotations


def print_expression(expr) -> str:
    from pathway_tpu.internals import expression as ex

    if isinstance(expr, ex.IdExpression):
        return f"{_tab(expr.table)}.id"
    if isinstance(expr, ex.ColumnReference):
        return f"{_tab(expr.table)}.{expr.name}"
    if isinstance(expr, ex.ConstExpression):
        return repr(expr._value)
    if isinstance(expr, ex.BinaryExpression):
        return f"({print_expression(expr._left)} {expr._op} {print_expression(expr._right)})"
    if isinstance(expr, ex.UnaryExpression):
        return f"({expr._op}{print_expression(expr._arg)})"
    if isinstance(expr, ex.IfElseExpression):
        return (f"if_else({print_expression(expr._if)}, "
                f"{print_expression(expr._then)}, {print_expression(expr._else)})")
    if isinstance(expr, ex.CoalesceExpression):
        return f"coalesce({', '.join(print_expression(a) for a in expr._args)})"
    if isinstance(expr, ex.ApplyExpression):
        fname = getattr(expr._fn, "__name__", "fn")
        return f"apply({fname}, {', '.join(print_expression(a) for a in expr._args)})"
    if isinstance(expr, ex.ReducerExpression):
        return f"reducers.{expr._name}({', '.join(print_expression(a) for a in expr._args)})"
    if isinstance(expr, ex.MethodCallExpression):
        args = ", ".join(print_expression(a) for a in expr._args[1:])
        return f"{print_expression(expr._args[0])}.{expr._method}({args})"
    if isinstance(expr, ex.CastExpression):
        return f"cast({expr._return_type!r}, {print_expression(expr._expr)})"
    if isinstance(expr, ex.MakeTupleExpression):
        return f"make_tuple({', '.join(print_expression(a) for a in expr._args)})"
    if isinstance(expr, ex.PointerExpression):
        return f"pointer_from({', '.join(print_expression(a) for a in expr._args)})"
    return f"<{type(expr).__name__}>"


def _tab(table) -> str:
    from pathway_tpu.internals.thisclass import ThisRef

    if isinstance(table, ThisRef):
        return f"pw.{table._kind}"
    name = getattr(table, "_name", None)
    return name or "<table>"
