"""Lowering + execution: Table plans → engine graph → microbatch run.

Rebuild of the reference's GraphRunner
(python/pathway/internals/graph_runner/__init__.py:36 +
expression_evaluator.py + operator_handler.py): walks the plan DAG reachable
from requested outputs, compiles expressions against row layouts, builds
engine operators, then drives the scheduler over logical times.
"""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import operators as eng
from pathway_tpu.engine.delta import Delta
from pathway_tpu.engine.graph import (
    CapturedStream,
    DemuxOperator,
    EngineGraph,
    IterateOperator,
    Node,
    Scheduler,
)
from pathway_tpu.internals import expression as ex
from pathway_tpu.internals.expression_compiler import (
    CompileContext,
    ExpressionCompiler,
    compile_map_program,
)
from pathway_tpu.internals.groupbys import split_reducers
from pathway_tpu.internals.keys import (Pointer, canonical_shard_value,
                                        hash_values, mix_pointers)
from pathway_tpu.internals.table import Plan, Table


class _Proxy:
    """Synthetic table for rewritten row spaces (groupby results etc.)."""

    def __init__(self, names):
        self._names = list(names)

    def _column_names(self):
        return self._names


def _referenced_tables(exprs, base: Table) -> list[Table]:
    """All concrete tables appearing in exprs, base first."""
    seen: dict[int, Table] = {id(base): base}
    order = [base]

    def walk(e):
        if isinstance(e, ex.ColumnReference) and isinstance(e.table, Table):
            if id(e.table) not in seen:
                seen[id(e.table)] = e.table
                order.append(e.table)
        for d in getattr(e, "_deps", ()):
            walk(d)

    for e in exprs:
        walk(e)
    return order


def _map_op_for(program, nondet: bool):
    """Map operator for a compiled program; device-dispatching programs
    (batch UDFs with device=True, e.g. the JAX encoder embedder) mark the
    operator device_bound so the scheduler pipelines it through the device
    bridge."""
    op = eng.DeterministicMapOperator(program) if nondet \
        else eng.MapOperator(program)
    if getattr(program, "device_bound", False):
        op.device_bound = True
    return op


class GraphRunner:
    def __init__(self):
        self.graph = EngineGraph()
        self._memo: dict[int, Node] = {}
        # (node, {time: [(key, row, diff)]}) — pre-grouped at lowering so
        # run startup does not rescan whole feeds row by row
        self._static_feeds: list[tuple[Node, dict]] = []
        self._stream_subjects: list[tuple[Node, Any]] = []  # streaming sources
        self._captures: dict[int, CapturedStream] = {}
        self._monitoring = None

    # ------------------------------------------------------------------
    # public entry points
    # ------------------------------------------------------------------
    def capture(self, table: Table) -> CapturedStream:
        node = self.lower(table)
        cap = CapturedStream()
        self.graph.add_node(eng.OutputOperator(cap.on_delta), [node], "capture")
        self._captures[id(table)] = cap
        return cap

    def subscribe(self, table: Table, callback: Callable[[int, Delta], None],
                  positions: bool = False) -> None:
        node = self.lower(table)
        self.graph.add_node(eng.OutputOperator(callback), [node], "subscribe")

    def run_batch(self, n_workers: int | None = None, cluster=None,
                  recorder=None) -> None:
        """Run all static feeds to completion (batch mode: one pass over the
        totally-ordered times present in the inputs + a flush tick). Under
        a cluster, static feeds are deterministic SPMD replicas: every
        process holds the same feed and keeps its worker block's shard.

        ``recorder`` threads a flight recorder through the scheduler
        (engine/flight_recorder.py); when omitted, the env wiring
        (``PATHWAY_TRACE_PATH`` / ``PATHWAY_FLIGHT_RECORDER``) decides —
        the default is None, costing one dead branch per operator step."""
        if n_workers is None:
            from pathway_tpu.internals.config import get_pathway_config

            n_workers = get_pathway_config().threads
        if recorder is None:
            from pathway_tpu.engine.flight_recorder import FlightRecorder

            recorder = FlightRecorder.from_env()
        sched = Scheduler(self.graph, n_workers=n_workers, cluster=cluster,
                          recorder=recorder)
        by_time, feed_times = self.static_feeds_by_time()
        times = {0} | feed_times
        try:
            for t in sorted(times):
                for node, groups in by_time:
                    batch = groups.get(t)
                    if batch:
                        sched.push_source(node, Delta(batch))
                sched.run_time(t)
            # end-of-stream flush tick: temporal buffers release held rows
            sched.run_time(max(times) + 1, flush=True)
        finally:
            sched.close()  # batch run complete: release worker pool threads
            self._scheduler = sched
            if recorder is not None:
                # trace survives a failing run — it is the post-mortem
                try:
                    recorder.write_chrome_trace()
                except Exception:
                    pass

    def static_feeds_by_time(self):
        """Feeds are stored pre-grouped by logical time (see _lower_static).
        Returns ([(node, {time: [(k, r, d)]})], set_of_times); shared by
        run_batch and the streaming runtime's startup feed."""
        times: set[int] = set()
        for _node, groups in self._static_feeds:
            times.update(groups)
        return self._static_feeds, times

    # ------------------------------------------------------------------
    # lowering
    # ------------------------------------------------------------------
    def lower(self, table: Table) -> Node:
        key = id(table)
        if key in self._memo:
            return self._memo[key]
        plan = table._plan
        handler = getattr(self, f"_lower_{plan.kind}", None)
        if handler is None:
            raise NotImplementedError(f"no lowering for plan kind {plan.kind!r}")
        node = handler(table, plan)
        if node.trace is None:
            node.trace = getattr(plan, "trace", None)
        if getattr(node, "error_log", None) is None:
            node.error_log = getattr(plan, "error_log", None)
        self._memo[key] = node
        return node

    # -- helpers ------------------------------------------------------------
    def _row_space(self, base: Table, exprs: list) -> tuple[Node, CompileContext]:
        """Node producing zipped rows of all tables referenced by exprs,
        with a CompileContext mapping references to positions."""
        tables = _referenced_tables(exprs, base)
        ctx = CompileContext()
        node = self.lower(tables[0])
        offset = ctx.add_table(tables[0], 0)
        for t in tables[1:]:
            right = self.lower(t)
            left_len = offset
            right_len = len(t._column_names())

            def combine(l, r, _ll=left_len, _rl=right_len):
                if l is None or r is None:
                    return None
                return (*l, *r)

            node = self.graph.add_node(
                eng.BinaryKeyOperator(combine), [node, right], "zip"
            )
            offset = ctx.add_table(t, offset)
        return node, ctx

    # -- sources ------------------------------------------------------------
    def _lower_static(self, table: Table, plan: Plan) -> Node:
        node = self.graph.add_source(table._name)
        keys = plan.params["keys"]
        rows = plan.params["rows"]
        times = plan.params.get("times")
        diffs = plan.params.get("diffs") or [1] * len(keys)
        groups: dict[int, list] = {}
        if times is None:
            groups[0] = [(k, tuple(r), d)
                         for k, r, d in zip(keys, rows, diffs)]
        else:
            for t, k, r, d in zip(times, keys, rows, diffs):
                g = groups.get(t)
                if g is None:
                    g = groups[t] = []
                g.append((k, tuple(r), d))
        self._static_feeds.append((node, groups))
        return node

    def _lower_input(self, table: Table, plan: Plan) -> Node:
        node = self.graph.add_source(table._name)
        self._stream_subjects.append((node, plan.params["datasource"]))
        return node

    def _lower_gradual_broadcast(self, table: Table, plan: Plan) -> Node:
        base = self.lower(plan.params["base"])
        thr = self.lower(plan.params["thr"])
        return self.graph.add_node(eng.GradualBroadcastOperator(),
                                   [base, thr], "gradual_broadcast")

    def _lower_identity(self, table: Table, plan: Plan) -> Node:
        return self.lower(plan.params["base"])

    # -- row ops ------------------------------------------------------------
    def _lower_map(self, table: Table, plan: Plan) -> Node:
        base = plan.params["base"]
        exprs = plan.params["exprs"]
        node, ctx = self._row_space(base, exprs)
        split_node = self._lower_map_split(table, exprs, node, ctx)
        if split_node is not None:
            return split_node
        program, nondet = compile_map_program(exprs, ctx)
        return self.graph.add_node(_map_op_for(program, nondet), [node],
                                   f"map:{table._name}")

    def _lower_map_split(self, table: Table, exprs, node, ctx) -> Node | None:
        """WindVE-style host/device split (internals/autojit.py): a select
        that carries BOTH auto-jit-fusable UDF chains and host-only UDFs
        lowers into two map operators over the same input — the fused part
        marked device_bound so it rides the pipelined bridge leg, the
        host-only part stepped on the host thread *while* a previous
        tick's device leg is still in flight — recombined by a stateless
        aligned zip. One operator (today's behavior) would serialize the
        host-only UDF time before the device dispatch every tick."""
        try:
            from pathway_tpu.internals.autojit import split_map_exprs

            split = split_map_exprs(exprs)
        except Exception:
            split = None
        if split is None:
            return None
        dev_idx, host_idx = split
        dev_program, dev_nd = compile_map_program(
            [exprs[i] for i in dev_idx], ctx)
        host_program, host_nd = compile_map_program(
            [exprs[i] for i in host_idx], ctx)

        def bail():
            # the full-program compile below builds its own FusedProgram
            # for these exprs — back the split's out of the registry and
            # counter, or /metrics reports phantom programs
            from pathway_tpu.internals.autojit import discard_programs

            discard_programs(dev_program.autojit)
            discard_programs(host_program.autojit)
            return None

        if dev_program.autojit is None or dev_nd or host_nd:
            # fusion did not engage after all, or a side needs the
            # caching DeterministicMapOperator (which reorders entries —
            # the aligned zip requires order preservation): single node
            return bail()
        if getattr(host_program, "device_bound", False):
            # the "host" side carries a device=True batch UDF: both maps
            # would ride the device leg, making the split pure overhead
            return bail()
        spec = [None] * len(exprs)
        for j, i in enumerate(host_idx):
            spec[i] = (0, j)
        for j, i in enumerate(dev_idx):
            spec[i] = (1, j)
        host_node = self.graph.add_node(
            eng.MapOperator(host_program), [node],
            f"map_host:{table._name}")
        dev_node = self.graph.add_node(
            _map_op_for(dev_program, dev_nd), [node],
            f"map_dev:{table._name}")
        return self.graph.add_node(
            eng.ZipAlignedOperator(tuple(spec)), [host_node, dev_node],
            f"map:{table._name}")

    def _lower_filter(self, table: Table, plan: Plan) -> Node:
        base = plan.params["base"]
        pred = plan.params["pred"]
        node, ctx = self._row_space(base, [pred])
        comp = ExpressionCompiler(ctx)
        # keep base row shape: need projection back to base columns if zipped
        pred_fn = comp.compile_predicate(pred)
        n_base = len(base._column_names())

        def keep_base(keys, rows):
            return [r[:n_base] for r in rows]

        filt = self.graph.add_node(eng.FilterOperator(pred_fn), [node], "filter")
        if len(_referenced_tables([pred], base)) > 1:
            return self.graph.add_node(eng.MapOperator(keep_base), [filt], "proj")
        return filt

    def _lower_filter_raw(self, table: Table, plan: Plan) -> Node:
        """Filter with a prebuilt batch predicate fn(keys, rows) -> [bool]
        (Table.remove_errors)."""
        base = self.lower(plan.params["base"])
        return self.graph.add_node(
            eng.FilterOperator(plan.params["pred_fn"]), [base], "filter_raw")

    def _lower_reindex(self, table: Table, plan: Plan) -> Node:
        base = plan.params["base"]
        key_exprs = plan.params["key_exprs"]
        node, ctx = self._row_space(base, key_exprs)
        comp = ExpressionCompiler(ctx)
        if plan.params.get("raw"):
            vfn = comp.compile(key_exprs[0])

            def key_fn(keys, rows):
                out = []
                for v in vfn(keys, rows):
                    if not isinstance(v, Pointer):
                        v = hash_values(v)
                    out.append(v)
                return out
        else:
            key_fn = comp.compile_key_fn(key_exprs)
        n_base = len(base._column_names())
        reindexed = self.graph.add_node(
            eng.ReindexOperator(key_fn), [node], "reindex")
        if len(_referenced_tables(key_exprs, base)) > 1:
            return self.graph.add_node(
                eng.MapOperator(lambda keys, rows: [r[:n_base] for r in rows]),
                [reindexed], "proj")
        return reindexed

    # -- groupby ------------------------------------------------------------
    def _lower_groupby(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        by = plan.params["by"]
        instance = plan.params["instance"]
        out_exprs = plan.params["out_exprs"]
        by_id = plan.params.get("by_id", False)

        gvals_exprs = list(by)
        if instance is not None:
            gvals_exprs.append(instance)

        n_red_placeholder: list = []
        proxy = _Proxy([])
        rewritten, reducers = split_reducers(out_exprs, by, instance, proxy)
        proxy._names = [f"__g{i}" for i in range(len(gvals_exprs))] + [
            f"__r{j}" for j in range(len(reducers))
        ]

        # compile group-side fns over base rows
        inner_exprs = list(gvals_exprs)
        for r in reducers:
            inner_exprs.extend(r._args)
        node, ctx = self._row_space(base, inner_exprs)
        comp = ExpressionCompiler(ctx)
        gval_fns = [comp.compile_row(e) for e in gvals_exprs]
        reducer_specs = []
        force_sort = False
        for r in reducers:
            arg_fns = [comp.compile_row(a) for a in r._args]
            name = _engine_reducer_name(r)
            if name in ("sum", "float_sum", "avg", "array_sum") and r._args:
                # float addition is not associative: keep the canonical
                # per-tick sort unless the argument is provably integral
                from pathway_tpu.internals import dtype as _dt
                from pathway_tpu.internals.type_inference import infer_dtype

                d = _dt.unoptionalize(infer_dtype(r._args[0]))
                if d not in (_dt.INT, _dt.BOOL):
                    force_sort = True
            kwargs = dict(r._kwargs)
            fn = kwargs.pop("fn", None)
            spec_kwargs = {}
            if name in ("sorted_tuple", "tuple", "ndarray"):
                spec_kwargs["skip_nones"] = kwargs.get("skip_nones", False)
            if name == "stateful":
                spec_kwargs["fn"] = fn
                if kwargs.get("emit") is not None:
                    spec_kwargs["emit"] = kwargs["emit"]
            if name == "argmin":
                def extract(key, row, _fns=arg_fns):
                    vals = [f(key, row) for f in _fns]
                    return (vals[0], key) if len(vals) == 1 else (vals[0], vals[1])
                reducer_specs.append(("argmin", extract, spec_kwargs))
                continue
            if name == "argmax":
                def extract(key, row, _fns=arg_fns):
                    vals = [f(key, row) for f in _fns]
                    return (vals[0], key) if len(vals) == 1 else (vals[0], vals[1])
                reducer_specs.append(("argmax", extract, spec_kwargs))
                continue
            if name in ("tuple", "ndarray"):
                def extract(key, row, _fns=arg_fns, _k=name):
                    return (_fns[0](key, row), int(key))
                reducer_specs.append((name, extract, spec_kwargs))
                continue

            if len(arg_fns) == 1:
                def extract(key, row, _fn=arg_fns[0]):
                    return (_fn(key, row),)
            else:
                def extract(key, row, _fns=arg_fns):
                    return tuple(f(key, row) for f in _fns)

            reducer_specs.append((name, extract, spec_kwargs))

        use_raw_key = bool(by_id)

        columnar = None
        if not force_sort and not use_raw_key:
            columnar = _columnar_groupby_spec(gvals_exprs, reducers, ctx)
        if columnar is not None:
            gnode = self.graph.add_node(
                eng.ColumnarGroupByOperator(*columnar),
                [node], f"groupby:{table._name}")
        else:
            if len(gval_fns) == 1 and not use_raw_key:
                def group_fn(key, row, _f=gval_fns[0]):
                    v = _f(key, row)
                    return hash_values(v), (v,)
            else:
                def group_fn(key, row):
                    gvals = tuple(f(key, row) for f in gval_fns)
                    if use_raw_key:
                        gkey = gvals[0] if isinstance(gvals[0], Pointer) else hash_values(gvals[0])
                    else:
                        gkey = hash_values(*gvals)
                    return gkey, gvals

            gnode = self.graph.add_node(
                eng.GroupByOperator(group_fn, reducer_specs,
                                    force_order_sensitive=force_sort),
                [node], f"groupby:{table._name}")

        # post-map over (gvals, reduced) rows; elided when it is the
        # identity projection (reduce() listing group cols then reducers in
        # storage order — the common case)
        if (len(rewritten) == len(proxy._names) and all(
                type(e) is ex.ColumnReference and e.table is proxy
                and e.name == proxy._names[i]
                for i, e in enumerate(rewritten))):
            return gnode
        post_ctx = CompileContext()
        post_ctx.add_table(proxy, 0)
        post_program, nondet = compile_map_program(rewritten, post_ctx)
        return self.graph.add_node(_map_op_for(post_program, nondet),
                                   [gnode], f"reduce:{table._name}")

    # -- joins --------------------------------------------------------------
    def _lower_join_select(self, table: Table, plan: Plan) -> Node:
        left: Table = plan.params["left"]
        right: Table = plan.params["right"]
        on = plan.params["on"]
        mode = plan.params["mode"]
        id_expr = plan.params.get("id_expr")
        exprs = plan.params["exprs"]

        lnode = self.lower(left)
        rnode = self.lower(right)

        lctx = CompileContext()
        lctx.add_table(left, 0)
        lcomp = ExpressionCompiler(lctx)
        l_fns = [lcomp.compile_row(a) for a, _ in on]
        rctx = CompileContext()
        rctx.add_table(right, 0)
        rcomp = ExpressionCompiler(rctx)
        r_fns = [rcomp.compile_row(b) for _, b in on]

        # SQL null semantics: a None join value matches nothing, but in
        # left/right/outer mode the row must still appear as an unmatched
        # "ear" — so map it to a per-row sentinel key that can't collide.
        # Hashable scalars are used RAW as the join-group key (dict keys in
        # the join state; the scheduler's route cache memoizes value →
        # worker) — hashing per row bought nothing. Bools still hash:
        # True == 1 as a dict key, but hash_values keeps them distinct,
        # and both sides must agree on the keying.
        def _jkey(v, side, key):
            if v is None:
                return ("__pw_null__", side, key)
            cls = v.__class__
            if cls is str or cls is Pointer:
                return v
            if cls is int:  # not bool: its class is bool
                return v
            if cls is bool:
                # True == 1 as a dict key but hash_values keeps bools
                # distinct from ints — a raw bool would falsely match an
                # int join key from the other side
                return hash_values(v)
            # floats / np scalars canonicalize so equal ints and floats
            # (1 vs 1.0, np.int64(1) vs 1) join exactly as the hash
            # encoding says they do; NaN and exotica fall back to hashing
            return canonical_shard_value(v)

        if len(l_fns) == 1:
            def lkey_fn(key, row, _f=l_fns[0]):
                return _jkey(_f(key, row), "l", key)
        else:
            def lkey_fn(key, row):
                vals = tuple(f(key, row) for f in l_fns)
                if any(v is None for v in vals):
                    return ("__pw_null__", "l", key)
                return hash_values(*vals)

        if len(r_fns) == 1:
            def rkey_fn(key, row, _f=r_fns[0]):
                return _jkey(_f(key, row), "r", key)
        else:
            def rkey_fn(key, row):
                vals = tuple(f(key, row) for f in r_fns)
                if any(v is None for v in vals):
                    return ("__pw_null__", "r", key)
                return hash_values(*vals)

        # plain-column join keys: give the native pass the position so it
        # extracts + canonicalizes inline (fallback reproduces _jkey)
        lkey_pos = rkey_pos = None
        if len(on) == 1 and type(on[0][0]) is ex.ColumnReference:
            try:
                lkey_pos = lctx.position(on[0][0])
            except KeyError:
                lkey_pos = None
        if len(on) == 1 and type(on[0][1]) is ex.ColumnReference:
            try:
                rkey_pos = rctx.position(on[0][1])
            except KeyError:
                rkey_pos = None
        key_kw = dict(
            lkey_pos=lkey_pos,
            lkey_fb=(lambda v, key: _jkey(v, "l", key))
            if lkey_pos is not None else None,
            rkey_pos=rkey_pos,
            rkey_fb=(lambda v, key: _jkey(v, "r", key))
            if rkey_pos is not None else None,
        )

        nl = len(left._column_names())
        nr = len(right._column_names())

        out_key_fn = None
        if id_expr is not None and isinstance(id_expr, ex.IdExpression):
            if id_expr.table is left:
                out_key_fn = lambda lk, rk, jk: lk
            elif id_expr.table is right:
                out_key_fn = lambda lk, rk, jk: rk

        ctx = CompileContext()
        off = ctx.add_table(left, 0)
        off = ctx.add_table(right, off)
        ctx.id_pos = {id(left): nl + nr, id(right): nl + nr + 1}

        # When every selected expression is a plain column/id reference the
        # join emits the projected row DIRECTLY (code-generated picker) and
        # the whole select map node disappears — one tuple per output row
        # instead of three (wide row, column batch, zipped row).
        direct = _direct_join_projection(exprs, ctx, nl, nr, mode)
        if direct is not None:
            direct_fn, cspec = direct
            jnode = self.graph.add_node(
                eng.JoinOperator(mode, lkey_fn, rkey_fn, direct_fn,
                                 out_key_fn, out_spec=cspec, **key_kw),
                [lnode, rnode], f"join_select:{table._name}")
            return jnode

        def out_fn(lk, lrow, rk, rrow):
            lr = lrow if lrow is not None else (None,) * nl
            rr = rrow if rrow is not None else (None,) * nr
            return (*lr, *rr, lk, rk)

        jnode = self.graph.add_node(
            eng.JoinOperator(mode, lkey_fn, rkey_fn, out_fn, out_key_fn,
                             **key_kw),
            [lnode, rnode], f"join:{mode}")

        program, nondet = compile_map_program(exprs, ctx)
        return self.graph.add_node(_map_op_for(program, nondet), [jnode],
                                   f"join_select:{table._name}")

    # -- set ops ------------------------------------------------------------
    def _project_to_names(self, t: Table, names: list[str]) -> Node:
        node = self.lower(t)
        own = t._column_names()
        if own == names:
            return node
        pos = [own.index(n) for n in names]

        def proj(keys, rows):
            return [tuple(r[p] for p in pos) for r in rows]

        return self.graph.add_node(eng.MapOperator(proj), [node], "proj")

    def _lower_concat(self, table: Table, plan: Plan) -> Node:
        tables = plan.params["tables"]
        update = plan.params["update"]
        names = table._column_names()
        nodes = [self._project_to_names(t, names) for t in tables]

        def combine_rows(present: list):
            live = [r for r in present if r is not None]
            return live[-1] if update else live[0]

        return self.graph.add_node(
            eng.NAryConcatOperator(len(nodes), combine_rows, update=update),
            nodes, "concat")

    def _lower_concat_reindex(self, table: Table, plan: Plan) -> Node:
        tables = plan.params["tables"]
        names = table._column_names()
        nodes = []
        for i, t in enumerate(tables):
            n = self._project_to_names(t, names)
            salt = i

            def key_fn(keys, rows, _s=salt):
                return [hash_values(k, _s) for k in keys]

            nodes.append(self.graph.add_node(
                eng.ReindexOperator(key_fn), [n], f"reindex{i}"))

        def combine_rows(present):
            return next(r for r in present if r is not None)

        return self.graph.add_node(
            eng.NAryConcatOperator(len(nodes), combine_rows, update=False),
            nodes, "concat_reindex")

    def _lower_update_cells(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        other: Table = plan.params["other"]
        columns = plan.params["columns"]
        lnode = self.lower(base)
        rnode = self.lower(other)
        base_names = base._column_names()
        other_names = other._column_names()
        repl = {base_names.index(c): other_names.index(c) for c in columns}

        def combine(l, r):
            if l is None:
                return None
            if r is None:
                return l
            return tuple(
                r[repl[i]] if i in repl else v for i, v in enumerate(l)
            )

        return self.graph.add_node(
            eng.BinaryKeyOperator(combine), [lnode, rnode], "update_cells")

    def _lower_key_filter(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        other: Table = plan.params["other"]
        mode = plan.params["mode"]
        lnode = self.lower(base)
        rnode = self.lower(other)
        if mode in ("restrict", "intersect"):
            combine = lambda l, r: l if (l is not None and r is not None) else None
        elif mode == "difference":
            combine = lambda l, r: l if (l is not None and r is None) else None
        else:
            raise ValueError(mode)
        return self.graph.add_node(
            eng.BinaryKeyOperator(combine), [lnode, rnode], mode)

    def _lower_having(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        indexer = plan.params["indexer"]
        idx_table: Table = plan.params["indexer_table"]
        lnode = self.lower(base)
        inode = self.lower(idx_table)
        ctx = CompileContext()
        ctx.add_table(idx_table, 0)
        comp = ExpressionCompiler(ctx)
        vfn = comp.compile(indexer)

        def key_fn(keys, rows):
            return [v if isinstance(v, Pointer) else hash_values(v)
                    for v in vfn(keys, rows)]

        keyed = self.graph.add_node(
            eng.ReindexOperator(key_fn), [inode], "having_keys")
        combine = lambda l, r: l if (l is not None and r is not None) else None
        return self.graph.add_node(
            eng.BinaryKeyOperator(combine), [lnode, keyed], "having")

    # -- reshaping ----------------------------------------------------------
    def _lower_flatten(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        col = plan.params["col_name"]
        origin_id = plan.params.get("origin_id")
        node = self.lower(base)
        pos = base._column_names().index(col)

        def fn(key, row):
            val = row[pos]
            if val is None:
                return []
            out = []
            for i, elem in enumerate(val):
                # mix-derived child keys: parent keys are already uniform
                # 128-bit digests, so the multiply-xor mix preserves
                # uniformity at a fraction of a fresh blake2b per row
                # (same rationale as join output keys, keys.py:147)
                nk = mix_pointers(key, i)
                nr = list(row)
                nr[pos] = elem
                if origin_id is not None:
                    nr.append(key)
                out.append((nk, tuple(nr)))
            return out

        return self.graph.add_node(eng.FlattenOperator(fn), [node], "flatten")

    def _lower_sort(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        key_e = plan.params["key"]
        inst_e = plan.params["instance"]
        node, ctx = self._row_space(base, [key_e] + ([inst_e] if inst_e else []))
        comp = ExpressionCompiler(ctx)
        kfn = comp.compile_row(key_e)
        ifn = comp.compile_row(inst_e) if inst_e is not None else None

        def key_fn(key, row):
            return kfn(key, row)

        def instance_fn(key, row):
            return ifn(key, row) if ifn is not None else None

        return self.graph.add_node(
            eng.SortOperator(key_fn, instance_fn), [node], "sort")

    def _lower_dedupe(self, table: Table, plan: Plan) -> Node:
        base: Table = plan.params["base"]
        value_e = plan.params["value"]
        inst_e = plan.params["instance"]
        acceptor = plan.params["acceptor"]
        node = self.lower(base)
        ctx = CompileContext()
        ctx.add_table(base, 0)
        comp = ExpressionCompiler(ctx)
        vfn = comp.compile_row(value_e) if value_e is not None else None
        ifn = comp.compile_row(inst_e) if inst_e is not None else None

        def value_fn(key, row):
            return vfn(key, row) if vfn is not None else row

        def instance_fn(key, row):
            return ifn(key, row) if ifn is not None else 0

        return self.graph.add_node(
            eng.DeduplicateOperator(instance_fn, value_fn, acceptor),
            [node], "deduplicate")

    # -- pointer lookup ------------------------------------------------------
    def _lower_ix(self, table: Table, plan: Plan) -> Node:
        target: Table = plan.params["target"]
        ctx_table: Table = plan.params["ctx"]
        key_expr = plan.params["key_expr"]
        optional = plan.params["optional"]

        lnode, lctx = self._row_space(ctx_table, [key_expr])
        comp = ExpressionCompiler(lctx)
        kfn = comp.compile_row(key_expr)
        rnode = self.lower(target)

        def lkey_fn(key, row):
            k = kfn(key, row)
            # None lookup key: matches nothing, but in optional mode the
            # row must still surface with a None payload
            return ("__pw_null__", "l", key) if k is None else k

        def rkey_fn(key, row):
            return key

        nt = len(target._column_names())

        def out_fn(lk, lrow, rk, rrow):
            return rrow if rrow is not None else (None,) * nt

        mode = "left" if optional else "inner"
        return self.graph.add_node(
            eng.JoinOperator(mode, lkey_fn, rkey_fn, out_fn,
                             out_key_fn=lambda lk, rk, jk: lk),
            [lnode, rnode], "ix")

    # -- temporal low-level --------------------------------------------------
    def _lower_forget_immediately(self, table: Table, plan: Plan) -> Node:
        from pathway_tpu.engine.temporal_ops import ForgetImmediatelyOperator

        node = self.lower(plan.params["base"])
        return self.graph.add_node(ForgetImmediatelyOperator(), [node], "forget_now")

    def _lower_filter_out_forgetting(self, table: Table, plan: Plan) -> Node:
        from pathway_tpu.engine.temporal_ops import FilterOutForgettingOperator

        node = self.lower(plan.params["base"])
        return self.graph.add_node(FilterOutForgettingOperator(), [node],
                                   "filter_out_forgetting")

    def _lower_buffer(self, table: Table, plan: Plan) -> Node:
        return self._lower_time_column_op(table, plan, "buffer")

    def _lower_forget(self, table: Table, plan: Plan) -> Node:
        return self._lower_time_column_op(table, plan, "forget")

    def _lower_freeze(self, table: Table, plan: Plan) -> Node:
        return self._lower_time_column_op(table, plan, "freeze")

    def _lower_time_column_op(self, table: Table, plan: Plan, kind: str) -> Node:
        from pathway_tpu.engine import temporal_ops as tops

        base: Table = plan.params["base"]
        node, ctx = self._row_space(base, [plan.params["threshold"],
                                           plan.params["time"]])
        comp = ExpressionCompiler(ctx)
        thr_fn = comp.compile_row(plan.params["threshold"])
        time_fn = comp.compile_row(plan.params["time"])

        if kind == "buffer":
            op = tops.BufferOperator(thr_fn, time_fn)
        elif kind == "forget":
            op = tops.ForgetOperator(thr_fn, time_fn,
                                     plan.params.get("mark", False))
        else:
            op = tops.FreezeOperator(thr_fn, time_fn)
        return self.graph.add_node(op, [node], kind)

    # -- iterate -------------------------------------------------------------
    def _lower_iterate_result(self, table: Table, plan: Plan) -> Node:
        shared = plan.params["shared"]
        index = plan.params["index"]
        inode = self._lower_iterate_shared(shared)
        return self.graph.add_node(DemuxOperator(index), [inode],
                                   f"iterate_out{index}")

    def _lower_iterate_shared(self, shared) -> Node:
        key = ("iterate", id(shared))
        if key in self._memo:
            return self._memo[key]
        outer_nodes = [self.lower(t) for t in shared.input_tables]

        def builder(subgraph, iter_sources, extra_sources):
            sub = GraphRunner()
            sub.graph = subgraph
            for placeholder, src in zip(shared.iterated_placeholders, iter_sources):
                sub._memo[id(placeholder)] = src
            for placeholder, src in zip(shared.extra_placeholders, extra_sources):
                sub._memo[id(placeholder)] = src
            iter_out_nodes = [sub.lower(t) for t in shared.body_outputs]
            result_nodes = [sub.lower(t) for t in shared.result_tables]
            return iter_out_nodes, result_nodes

        op = IterateOperator(
            n_iterated=len(shared.iterated_placeholders),
            n_extra=len(shared.extra_placeholders),
            builder=builder,
            limit=shared.limit,
        )
        node = self.graph.add_node(op, outer_nodes, "iterate")
        self._memo[key] = node
        return node

    # -- external index ------------------------------------------------------
    def _lower_external_index(self, table: Table, plan: Plan) -> Node:
        from pathway_tpu.engine.index_ops import ExternalIndexOperator

        data: Table = plan.params["data"]
        queries: Table = plan.params["queries"]
        factory = plan.params["index_factory"]
        dnode = self.lower(data)
        qnode = self.lower(queries)

        def colpos(t, col):
            if col is None:
                return None
            name = col.name if isinstance(col, ex.ColumnReference) else col
            return t._column_names().index(name)

        op = ExternalIndexOperator(
            index=factory.build(),
            data_vec_pos=plan.params.get("data_vec_pos", 0),
            data_filter_pos=colpos(data, plan.params.get("data_filter_col")),
            query_vec_pos=plan.params.get("query_vec_pos", 0),
            query_limit_pos=colpos(queries, plan.params.get("limit_col")),
            query_filter_pos=colpos(queries, plan.params.get("query_filter_col")),
            revise=plan.params.get("revise", False),
        )
        return self.graph.add_node(op, [dnode, qnode], "external_index")


def _engine_reducer_name(r: ex.ReducerExpression) -> str:
    return r._name


def _direct_join_projection(exprs, ctx, nl: int, nr: int, mode: str):
    """``(out_fn, c_spec)`` when every select expression is a plain
    column/id reference; None otherwise. out_fn is a code-generated
    ``(lk, lrow, rk, rrow) -> projected row``; c_spec is the equivalent
    ((side, pos), ...) table for the native join pass (side 0 = left row,
    1 = right row, 2 = key with pos 0 lk / 1 rk). Replaces out_fn +
    select-map with a single tuple build per output row."""
    items = []
    cspec = []
    for e in exprs:
        if isinstance(e, ex.IdExpression):
            pos = ctx.id_pos.get(id(e.table))
            if pos == nl + nr:
                items.append("lk")
                cspec.append((2, 0))
            elif pos == nl + nr + 1:
                items.append("rk")
                cspec.append((2, 1))
            else:
                return None
        elif type(e) is ex.ColumnReference:
            try:
                p = ctx.position(e)
            except KeyError:
                return None
            if p < nl:
                items.append(f"lrow[{p}]")
                cspec.append((0, p))
            else:
                items.append(f"rrow[{p - nl}]")
                cspec.append((1, p - nl))
        else:
            return None
    body = f"({', '.join(items)},)" if items else "()"
    if mode == "inner":  # both rows always present
        fn = eval(f"lambda lk, lrow, rk, rrow: {body}")  # noqa: S307
        return fn, tuple(cspec)
    fn = eval(  # noqa: S307 — outer modes: absent side reads as None
        f"lambda lk, lrow, rk, rrow, _ln=(None,) * {nl}, _rn=(None,) * {nr}: "
        f"(lambda lrow, rrow: {body})("
        "lrow if lrow is not None else _ln, "
        "rrow if rrow is not None else _rn)")
    return fn, tuple(cspec)


_COLUMNAR_GVAL_DTYPES = None  # populated lazily (dtype import cycle)


def _columnar_groupby_spec(gvals_exprs, reducers, ctx):
    """Positions for ColumnarGroupByOperator, or None if ineligible.

    Eligible: every group value is a plain column of hashable scalar dtype
    and every reducer is count / integral sum / integral avg. The hash
    semantics are preserved exactly — the operator aliases typed intern
    keys through ``hash_values`` on first sight of each distinct value."""
    global _COLUMNAR_GVAL_DTYPES
    from pathway_tpu.internals import dtype as _dt
    from pathway_tpu.internals.type_inference import infer_dtype

    if _COLUMNAR_GVAL_DTYPES is None:
        _COLUMNAR_GVAL_DTYPES = (
            _dt.INT, _dt.BOOL, _dt.STR, _dt.FLOAT, _dt.POINTER,
            _dt.DATE_TIME_NAIVE, _dt.DATE_TIME_UTC, _dt.DURATION,
        )

    def hashable_dtype(d) -> bool:
        d = _dt.unoptionalize(d)
        if d in _COLUMNAR_GVAL_DTYPES:
            return True
        # concrete scalar tuples (window keys: (instance, start, end))
        # intern fine — tuple hashing over hashable members
        if isinstance(d, _dt.Tuple):
            return all(hashable_dtype(el) for el in d.args)
        return False

    gval_pos = []
    for e in gvals_exprs:
        if isinstance(e, ex.IdExpression) or type(e) is not ex.ColumnReference:
            return None
        try:
            if not hashable_dtype(infer_dtype(e)):
                return None
        except Exception:
            return None
        gval_pos.append(ctx.position(e))
    reducer_cols = []
    for r in reducers:
        name = _engine_reducer_name(r)
        if name == "count" and not r._args:
            reducer_cols.append(("count", None))
            continue
        if name in ("sum", "int_sum", "avg") and len(r._args) == 1:
            a = r._args[0]
            if type(a) is not ex.ColumnReference:
                return None
            try:
                d = _dt.unoptionalize(infer_dtype(a))
            except Exception:
                return None
            if d not in (_dt.INT, _dt.BOOL):
                return None
            reducer_cols.append(
                ("avg" if name == "avg" else "sum", ctx.position(a)))
            continue
        if name in ("min", "max") and len(r._args) == 1:
            # multiset side-state in the columnar operator: exact under
            # retraction, values must be hashable scalars
            a = r._args[0]
            if type(a) is not ex.ColumnReference:
                return None
            try:
                if not hashable_dtype(infer_dtype(a)):
                    return None
            except Exception:
                return None
            reducer_cols.append((name, ctx.position(a)))
            continue
        if name in ("argmin", "argmax") and len(r._args) in (1, 2):
            # (cmp, payload) multiset; payload defaults to the row key
            # (runner's argmin extract semantics, position -1)
            positions = []
            for a in r._args:
                if type(a) is not ex.ColumnReference:
                    return None
                try:
                    if not hashable_dtype(infer_dtype(a)):
                        return None
                except Exception:
                    return None
                positions.append(ctx.position(a))
            if len(positions) == 1:
                positions.append(-1)  # payload = row key
            reducer_cols.append((name, tuple(positions)))
            continue
        return None
    return gval_pos, reducer_cols


# ---------------------------------------------------------------------------
# convenience: run tables to captured streams (test harness backbone)
# ---------------------------------------------------------------------------

def run_tables(*tables: Table) -> list[CapturedStream]:
    runner = GraphRunner()
    caps = [runner.capture(t) for t in tables]
    runner.run_batch()
    return caps
