"""Retry strategies shared by async UDF execution and connector supervision.

Hoisted out of ``internals/udfs.py`` so the two retry consumers — async UDF
invocation (udfs.py ``_wrap_async``) and the streaming runtime's connector
supervisor (engine/supervisor.py) — use one implementation of the delay
schedule (reference: python/pathway/internals/udfs/retries.py; the engine
side's connector restart backoff lives in src/connectors/mod.rs).

The strategies expose two surfaces over the same schedule:

- ``delay_for_attempt(attempt)`` — the synchronous schedule: seconds to wait
  before retry number ``attempt`` (0-based). The supervisor consumes this
  directly; it is also the unit-testable contract.
- ``invoke(fn, *args, **kwargs)`` — the async combinator wrapping a
  coroutine call with up to ``max_retries`` retries, sleeping the schedule
  between attempts. UDF executors consume this.

``ExponentialBackoffRetryStrategy`` supports a ``max_delay_ms`` cap and
full jitter (AWS-style: uniform over ``[0, capped_delay]``), seeded for
deterministic schedules under test.
"""

from __future__ import annotations

import asyncio
import random
from typing import Callable


class AsyncRetryStrategy:
    """Base strategy: subclasses define the schedule and retry budget."""

    async def invoke(self, fn: Callable, /, *args, **kwargs):
        raise NotImplementedError

    def delay_for_attempt(self, attempt: int) -> float:
        """Seconds to wait before retry ``attempt`` (0-based)."""
        raise NotImplementedError


class NoRetryStrategy(AsyncRetryStrategy):
    async def invoke(self, fn, /, *args, **kwargs):
        return await fn(*args, **kwargs)

    def delay_for_attempt(self, attempt: int) -> float:
        raise RuntimeError("NoRetryStrategy never retries")


class FixedDelayRetryStrategy(AsyncRetryStrategy):
    """Retry up to ``max_retries`` times with a constant pause between
    attempts."""

    def __init__(self, max_retries: int = 3, delay_ms: int = 1000):
        self.max_retries = max_retries
        self.delay_ms = delay_ms

    def delay_for_attempt(self, attempt: int) -> float:
        return self.delay_ms / 1000

    async def invoke(self, fn, /, *args, **kwargs):
        for attempt in range(self.max_retries + 1):
            try:
                return await fn(*args, **kwargs)
            except asyncio.CancelledError:
                raise
            except Exception:
                if attempt == self.max_retries:
                    raise
                await asyncio.sleep(self.delay_for_attempt(attempt))
        raise RuntimeError("unreachable")


class ExponentialBackoffRetryStrategy(FixedDelayRetryStrategy):
    """Exponential schedule ``initial * factor**attempt``, capped at
    ``max_delay_ms``, with optional full jitter.

    Full jitter draws each delay uniformly from ``[0, capped_delay]`` —
    the schedule that de-synchronizes a fleet of failing connectors
    hammering one endpoint. Pass ``seed`` for a deterministic draw
    sequence (tests; reproducing an incident's timing).
    """

    def __init__(self, max_retries: int = 3, initial_delay_ms: int = 1000,
                 backoff_factor: float = 2.0,
                 max_delay_ms: int | None = None,
                 jitter: bool = False, seed: int | None = None):
        super().__init__(max_retries, initial_delay_ms)
        self.backoff_factor = backoff_factor
        self.max_delay_ms = max_delay_ms
        self.jitter = jitter
        self._rng = random.Random(seed)

    def delay_for_attempt(self, attempt: int) -> float:
        delay_ms = self.delay_ms * self.backoff_factor ** attempt
        if self.max_delay_ms is not None:
            delay_ms = min(delay_ms, self.max_delay_ms)
        if self.jitter:
            delay_ms = self._rng.uniform(0.0, delay_ms)
        return delay_ms / 1000
