"""Best-effort static dtype inference for expressions.

Lightweight stand-in for the reference's full type checker
(python/pathway/internals/type_interpreter.py): enough to give result
schemas correct dtypes for the common cases, degrading to ANY instead of
raising when unsure.
"""

from __future__ import annotations

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as ex

_CMP = {"==", "!=", "<", "<=", ">", ">="}
_BOOL = {"&", "|", "^"}

_REDUCER_TYPES = {
    "count": lambda args: dt.INT,
    "sum": lambda args: args[0] if args else dt.INT,
    "int_sum": lambda args: dt.INT,
    "float_sum": lambda args: dt.FLOAT,
    "array_sum": lambda args: dt.ANY_ARRAY,
    "avg": lambda args: dt.FLOAT,
    "min": lambda args: args[0] if args else dt.ANY,
    "max": lambda args: args[0] if args else dt.ANY,
    # one arg: the best row's KEY; two args: the payload expression's value
    "argmin": lambda args: args[1] if len(args) > 1 else dt.POINTER,
    "argmax": lambda args: args[1] if len(args) > 1 else dt.POINTER,
    "unique": lambda args: args[0] if args else dt.ANY,
    "any": lambda args: args[0] if args else dt.ANY,
    "sorted_tuple": lambda args: dt.List(args[0]) if args else dt.ANY_TUPLE,
    "tuple": lambda args: dt.List(args[0]) if args else dt.ANY_TUPLE,
    "ndarray": lambda args: dt.ANY_ARRAY,
    "earliest": lambda args: args[0] if args else dt.ANY,
    "latest": lambda args: args[0] if args else dt.ANY,
    "stateful": lambda args: dt.ANY,
}

_METHOD_TYPES = {
    "to_string": dt.STR,
    "num.abs": None,  # same as arg
    "num.round": None,
    "num.fill_na": None,
    "str.len": dt.INT,
    "str.count": dt.INT,
    "str.find": dt.INT,
    "str.rfind": dt.INT,
    "str.startswith": dt.BOOL,
    "str.endswith": dt.BOOL,
    "str.parse_int": dt.INT,
    "str.parse_float": dt.FLOAT,
    "str.parse_bool": dt.BOOL,
    "str.split": dt.List(dt.STR),
    "str.rsplit": dt.List(dt.STR),
    "dt.strftime": dt.STR,
    "dt.strptime": dt.DATE_TIME_NAIVE,
    "dt.timestamp": dt.INT,
    "dt.from_timestamp": dt.DATE_TIME_NAIVE,
    "dt.utc_from_timestamp": dt.DATE_TIME_UTC,
    "dt.to_utc": dt.DATE_TIME_UTC,
    "dt.to_naive_in_timezone": dt.DATE_TIME_NAIVE,
}
for _m in ("nanosecond", "microsecond", "millisecond", "second", "minute",
           "hour", "day", "month", "year", "weekday", "nanoseconds",
           "microseconds", "milliseconds", "seconds", "minutes", "hours",
           "days", "weeks"):
    _METHOD_TYPES[f"dt.{_m}"] = dt.INT
for _m in ("lower", "upper", "reversed", "strip", "lstrip", "rstrip",
           "swapcase", "title", "capitalize", "casefold", "removeprefix",
           "removesuffix", "replace", "slice"):
    _METHOD_TYPES[f"str.{_m}"] = dt.STR


def infer_dtype(expr: ex.ColumnExpression) -> dt.DType:
    try:
        return _infer(expr)
    except Exception:
        return dt.ANY


def _infer(expr: ex.ColumnExpression) -> dt.DType:
    if isinstance(expr, ex.IdExpression):
        return dt.POINTER
    if isinstance(expr, ex.ColumnReference):
        table = expr.table
        schema = getattr(table, "schema", None)
        if schema is not None:
            try:
                return schema[expr.name].dtype
            except KeyError:
                return dt.ANY
        return dt.ANY
    if isinstance(expr, ex.ConstExpression):
        return dt.wrap(type(expr._value)) if expr._value is not None else dt.NONE
    if isinstance(expr, ex.BinaryExpression):
        if expr._op in _CMP:
            return dt.BOOL
        lt, rt = _infer(expr._left), _infer(expr._right)
        if expr._op in _BOOL:
            return dt.BOOL if lt is dt.BOOL or rt is dt.BOOL else dt.types_lca(lt, rt)
        if expr._op == "/":
            if dt.unoptionalize(lt) in (dt.INT, dt.FLOAT):
                return dt.FLOAT
            return dt.types_lca(lt, rt)
        if expr._op == "-" and {dt.unoptionalize(lt), dt.unoptionalize(rt)} <= {
                dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC}:
            return dt.DURATION
        if expr._op == "@":
            return dt.ANY_ARRAY
        return dt.types_lca(lt, rt)
    if isinstance(expr, ex.UnaryExpression):
        return _infer(expr._arg)
    if isinstance(expr, (ex.IsNoneExpression, ex.IsNotNoneExpression)):
        return dt.BOOL
    if isinstance(expr, ex.IfElseExpression):
        return dt.types_lca(_infer(expr._then), _infer(expr._else))
    if isinstance(expr, ex.CoalesceExpression):
        out = dt.NONE
        for a in expr._args:
            out = dt.types_lca(out, _infer(a))
        for a in expr._args:
            if not dt.is_optional(_infer(a)):
                return dt.unoptionalize(out)
        return out
    if isinstance(expr, ex.RequireExpression):
        return dt.Optional(dt.unoptionalize(_infer(expr._val)))
    if isinstance(expr, (ex.CastExpression, ex.ConvertExpression,
                         ex.DeclareTypeExpression)):
        return expr._return_type
    if isinstance(expr, ex.UnwrapExpression):
        return dt.unoptionalize(_infer(expr._expr))
    if isinstance(expr, ex.FillErrorExpression):
        return dt.types_lca(_infer(expr._expr), _infer(expr._replacement))
    if isinstance(expr, ex.ApplyExpression):
        return expr._return_type
    if isinstance(expr, ex.ReducerExpression):
        arg_types = [_infer(a) for a in expr._args]
        fn = _REDUCER_TYPES.get(expr._name)
        return fn(arg_types) if fn else dt.ANY
    if isinstance(expr, ex.MethodCallExpression):
        t = _METHOD_TYPES.get(expr._method, dt.ANY)
        if t is None:
            return _infer(expr._args[0])
        return t
    if isinstance(expr, ex.PointerExpression):
        return dt.POINTER
    if isinstance(expr, ex.MakeTupleExpression):
        return dt.Tuple(*[_infer(a) for a in expr._args])
    if isinstance(expr, ex.GetExpression):
        obj_t = dt.unoptionalize(_infer(expr._obj))
        if obj_t is dt.JSON:
            return dt.JSON
        if isinstance(obj_t, dt.Tuple) and isinstance(expr._index, ex.ConstExpression):
            i = expr._index._value
            if isinstance(i, int) and -len(obj_t.args) <= i < len(obj_t.args):
                return obj_t.args[i]
        if isinstance(obj_t, dt.List):
            return obj_t.wrapped
        return dt.ANY
    return dt.ANY
