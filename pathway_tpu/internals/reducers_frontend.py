"""`pw.reducers` namespace (reference: python/pathway/reducers →
internals/custom_reducers.py + engine Reducer enum, src/engine/reduce.rs:22)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.internals import expression as ex


def count(*args) -> ex.ReducerExpression:
    return ex.ReducerExpression("count", *args)


def sum(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("sum", expr)


def avg(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("avg", expr)


def min(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("min", expr)


def max(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("max", expr)


def argmin(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("argmin", expr)


def argmax(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("argmax", expr)


def unique(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("unique", expr)


def any(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("any", expr)


def sorted_tuple(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:
    return ex.ReducerExpression("sorted_tuple", expr, skip_nones=skip_nones)


def tuple(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("tuple", expr, skip_nones=skip_nones)


def ndarray(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:
    return ex.ReducerExpression("ndarray", expr, skip_nones=skip_nones)


def earliest(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("earliest", expr)


def latest(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("latest", expr)


def stateful_single(combine_fn: Callable, *args) -> ex.ReducerExpression:
    def combine(state, rows):
        for row in rows:
            state = combine_fn(state, *row)
        return state

    return ex.ReducerExpression("stateful", *args, fn=combine)


def stateful_many(combine_fn: Callable, *args) -> ex.ReducerExpression:
    return ex.ReducerExpression("stateful", *args, fn=combine_fn)


def udf_reducer(reducer_cls):
    """Decorator-compatible custom reducer hook (subset of reference API)."""

    def make(*args):
        acc = reducer_cls()

        def combine(state, rows):
            if state is None:
                state = acc.initial_state() if hasattr(acc, "initial_state") else None
            for row in rows:
                state = acc.update(state, *row)
            return state

        return ex.ReducerExpression("stateful", *args, fn=combine)

    return make
