"""`pw.reducers` namespace (reference: python/pathway/reducers →
internals/custom_reducers.py + engine Reducer enum, src/engine/reduce.rs:22).

>>> import pathway_tpu as pw
>>> t = pw.debug.table_from_markdown(\'\'\'
... shop | qty
... a    | 3
... a    | 5
... b    | 2
... \'\'\')
>>> pw.debug.compute_and_print(
...     t.groupby(t.shop).reduce(
...         t.shop, n=pw.reducers.count(), total=pw.reducers.sum(t.qty),
...         top=pw.reducers.max(t.qty)),
...     include_id=False)
shop | n | total | top
a | 2 | 8 | 5
b | 1 | 2 | 2
"""

from __future__ import annotations

from typing import Callable

from pathway_tpu.internals import expression as ex


def count(*args) -> ex.ReducerExpression:
    return ex.ReducerExpression("count", *args)


def sum(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("sum", expr)


def avg(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("avg", expr)


def min(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("min", expr)


def max(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("max", expr)


def argmin(expr, *payload) -> ex.ReducerExpression:
    """One arg: key of the row holding the min. Two args: the second
    expression's value from that row (engine argmin payload form)."""
    return ex.ReducerExpression("argmin", expr, *payload)


def argmax(expr, *payload) -> ex.ReducerExpression:
    """One arg: key of the row holding the max. Two args: the second
    expression's value from that row (engine argmax payload form)."""
    return ex.ReducerExpression("argmax", expr, *payload)


def unique(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("unique", expr)


def any(expr) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("any", expr)


def sorted_tuple(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:
    return ex.ReducerExpression("sorted_tuple", expr, skip_nones=skip_nones)


def tuple(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:  # noqa: A001
    return ex.ReducerExpression("tuple", expr, skip_nones=skip_nones)


def ndarray(expr, *, skip_nones: bool = False) -> ex.ReducerExpression:
    return ex.ReducerExpression("ndarray", expr, skip_nones=skip_nones)


def earliest(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("earliest", expr)


def latest(expr) -> ex.ReducerExpression:
    return ex.ReducerExpression("latest", expr)


def stateful_single(combine_fn: Callable, *args) -> ex.ReducerExpression:
    def combine(state, rows):
        for row in rows:
            state = combine_fn(state, *row)
        return state

    return ex.ReducerExpression("stateful", *args, fn=combine)


def stateful_many(combine_fn: Callable, *args) -> ex.ReducerExpression:
    return ex.ReducerExpression("stateful", *args, fn=combine_fn)


class BaseCustomAccumulator:
    """Custom-reducer protocol (reference: pw.BaseCustomAccumulator):
    ``from_row(row)`` builds a one-row accumulator, ``update(other)`` folds
    another accumulator in, ``compute_result()`` extracts the emitted value.
    Use with :func:`udf_reducer`."""

    @classmethod
    def from_row(cls, row):
        raise NotImplementedError

    def update(self, other) -> None:
        raise NotImplementedError

    def compute_result(self):
        raise NotImplementedError


def udf_reducer(reducer_cls):
    """Turn a BaseCustomAccumulator subclass (or a legacy
    ``update(state, *row)`` class) into a reducer factory."""

    if isinstance(reducer_cls, type) and issubclass(reducer_cls,
                                                    BaseCustomAccumulator):
        def make(*args):
            def combine(state, rows):
                for row in rows:
                    acc = reducer_cls.from_row(list(row))
                    if state is None:
                        state = acc
                    else:
                        state.update(acc)
                return state

            def emit(state):
                return state.compute_result()

            return ex.ReducerExpression("stateful", *args, fn=combine,
                                        emit=emit)

        return make

    def make(*args):
        acc = reducer_cls()

        def combine(state, rows):
            if state is None:
                state = acc.initial_state() if hasattr(acc, "initial_state") else None
            for row in rows:
                state = acc.update(state, *row)
            return state

        return ex.ReducerExpression("stateful", *args, fn=combine)

    return make


def int_sum(expr) -> ex.ReducerExpression:
    """Deprecated alias of ``sum`` (reference reducers.int_sum)."""
    return ex.ReducerExpression("sum", expr)


def npsum(expr) -> ex.ReducerExpression:
    """Deprecated alias of ``ndarray`` element-wise sum
    (reference reducers.npsum → array_sum)."""
    return ex.ReducerExpression("array_sum", expr)
