"""Class-based schemas (reference: python/pathway/internals/schema.py, 923 LoC).

``class MySchema(pw.Schema): x: int = pw.column_definition(primary_key=True)``
plus builders: schema_from_types / schema_from_dict / schema_builder /
schema_from_pandas / schema_from_csv, schema union via ``|``.
"""

from __future__ import annotations

import csv as _csv
import typing
from dataclasses import dataclass
from typing import Any, Mapping

from pathway_tpu.internals import dtype as dt


@dataclass
class ColumnDefinition:
    primary_key: bool = False
    default_value: Any = ...
    dtype: dt.DType | None = None
    name: str | None = None
    append_only: bool | None = None
    _description: str | None = None
    example: Any = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not ...


def column_definition(*, primary_key: bool = False, default_value: Any = ...,
                      dtype: Any = None, name: str | None = None,
                      append_only: bool | None = None, description: str | None = None,
                      example: Any = None) -> Any:
    return ColumnDefinition(
        primary_key=primary_key,
        default_value=default_value,
        dtype=dt.wrap(dtype) if dtype is not None else None,
        name=name,
        append_only=append_only,
        _description=description,
        example=example,
    )


@dataclass
class ColumnSchema:
    name: str
    dtype: dt.DType
    primary_key: bool = False
    default_value: Any = ...
    append_only: bool = False
    description: str | None = None

    @property
    def has_default_value(self) -> bool:
        return self.default_value is not ...

    @property
    def typehint(self):
        return self.dtype.typehint


class SchemaMetaclass(type):
    __columns__: dict[str, ColumnSchema]

    def __init__(cls, name, bases, namespace, append_only: bool | None = None,
                 **kwargs):
        super().__init__(name, bases, namespace)
        columns: dict[str, ColumnSchema] = {}
        for base in bases:
            if hasattr(base, "__columns__"):
                columns.update(base.__columns__)
        hints = {}
        try:
            hints = typing.get_type_hints(cls)
        except Exception:
            hints = dict(namespace.get("__annotations__", {}))
        for attr, hint in namespace.get("__annotations__", {}).items():
            if attr.startswith("__"):
                continue
            hint = hints.get(attr, hint)
            definition = namespace.get(attr, None)
            if not isinstance(definition, ColumnDefinition):
                definition = ColumnDefinition(
                    default_value=definition if attr in namespace else ...
                )
            col_dtype = definition.dtype or dt.wrap(hint)
            col_name = definition.name or attr
            columns[attr] = ColumnSchema(
                name=col_name,
                dtype=col_dtype,
                primary_key=definition.primary_key,
                default_value=definition.default_value,
                append_only=bool(
                    definition.append_only
                    if definition.append_only is not None
                    else (append_only or False)
                ),
                description=definition._description,
            )
        cls.__columns__ = columns

    # -- public api on schema classes --------------------------------------
    def column_names(cls) -> list[str]:
        return [c.name for c in cls.__columns__.values()]

    def columns(cls) -> Mapping[str, ColumnSchema]:
        return dict(cls.__columns__)

    def primary_key_columns(cls) -> list[str] | None:
        pkeys = [c.name for c in cls.__columns__.values() if c.primary_key]
        return pkeys or None

    def typehints(cls) -> dict[str, Any]:
        return {c.name: c.dtype.typehint for c in cls.__columns__.values()}

    def _dtypes(cls) -> dict[str, dt.DType]:
        return {c.name: c.dtype for c in cls.__columns__.values()}

    def default_values(cls) -> dict[str, Any]:
        return {
            c.name: c.default_value
            for c in cls.__columns__.values()
            if c.has_default_value
        }

    def keys(cls):
        return cls.column_names()

    def __getitem__(cls, name) -> ColumnSchema:
        for c in cls.__columns__.values():
            if c.name == name:
                return c
        raise KeyError(name)

    def __or__(cls, other):
        cols = {**cls.__columns__, **other.__columns__}
        return schema_from_columns(cols, name=f"{cls.__name__}|{other.__name__}")

    def __repr__(cls):
        body = ", ".join(f"{c.name}: {c.dtype!r}" for c in cls.__columns__.values())
        return f"<pw.Schema {cls.__name__}({body})>"

    def __eq__(cls, other):
        if not isinstance(other, SchemaMetaclass):
            return NotImplemented
        return cls._dtypes() == other._dtypes()

    def __hash__(cls):
        return hash(tuple(sorted((n, repr(d)) for n, d in cls._dtypes().items())))

    def with_types(cls, **kwargs):
        cols = dict(cls.__columns__)
        for name, hint in kwargs.items():
            if name not in cols:
                raise ValueError(f"no column {name!r} in schema")
            old = cols[name]
            cols[name] = ColumnSchema(
                name=old.name, dtype=dt.wrap(hint), primary_key=old.primary_key,
                default_value=old.default_value, append_only=old.append_only,
            )
        return schema_from_columns(cols, name=cls.__name__)

    def without(cls, *columns):
        names = {
            c if isinstance(c, str) else c.name for c in columns
        }
        cols = {k: v for k, v in cls.__columns__.items() if v.name not in names}
        return schema_from_columns(cols, name=cls.__name__)

    def update_properties(cls, **kwargs):
        return cls

    def universe_properties(cls):
        return None


class SchemaProperties:
    """Schema-wide properties (reference: internals/schema.py
    SchemaProperties — ``append_only`` marks every column append-only)."""

    def __init__(self, append_only: bool | None = None):
        self.append_only = append_only


class Schema(metaclass=SchemaMetaclass):
    """Base class for user schemas."""

    __properties__: "SchemaProperties | None" = None

    @classmethod
    def properties(cls) -> "SchemaProperties | None":
        return cls.__properties__


def schema_from_columns(columns: dict[str, ColumnSchema], name: str = "Schema"):
    cls = SchemaMetaclass(name, (Schema,), {})
    cls.__columns__ = dict(columns)
    return cls


def schema_from_types(_name: str = "Schema", **kwargs) -> type[Schema]:
    cols = {
        name: ColumnSchema(name=name, dtype=dt.wrap(hint))
        for name, hint in kwargs.items()
    }
    return schema_from_columns(cols, name=_name)


def schema_from_dict(columns: dict, name: str = "Schema") -> type[Schema]:
    cols = {}
    for cname, spec in columns.items():
        if isinstance(spec, dict):
            cols[cname] = ColumnSchema(
                name=cname,
                dtype=dt.wrap(spec.get("dtype", Any)),
                primary_key=spec.get("primary_key", False),
                default_value=spec.get("default_value", ...),
            )
        else:
            cols[cname] = ColumnSchema(name=cname, dtype=dt.wrap(spec))
    return schema_from_columns(cols, name=name)


def schema_builder(columns: dict[str, ColumnDefinition], *,
                   name: str = "Schema", properties=None) -> type[Schema]:
    schema_append_only = bool(getattr(properties, "append_only", False))
    cols = {}
    for cname, definition in columns.items():
        cols[cname] = ColumnSchema(
            name=definition.name or cname,
            dtype=definition.dtype or dt.ANY,
            primary_key=definition.primary_key,
            default_value=definition.default_value,
            append_only=bool(definition.append_only
                             or schema_append_only),
        )
    out = schema_from_columns(cols, name=name)
    out.__properties__ = properties
    return out


def schema_from_pandas(df, *, id_from=None, name: str = "Schema",
                       exclude_columns: set[str] = frozenset()) -> type[Schema]:
    import numpy as np

    cols = {}
    id_from = set(id_from or [])
    for cname in df.columns:
        if cname in exclude_columns:
            continue
        npdt = df[cname].dtype
        if npdt == np.dtype(object):
            sample = next((v for v in df[cname] if v is not None), None)
            cdt = dt.wrap(type(sample)) if sample is not None else dt.ANY
        else:
            cdt = dt.wrap(npdt)
        cols[cname] = ColumnSchema(
            name=cname, dtype=cdt, primary_key=cname in id_from
        )
    return schema_from_columns(cols, name=name)


def schema_from_csv(path: str, *, name: str = "Schema", properties=None,
                    delimiter: str = ",", comment_character: str | None = None,
                    quote: str = '"', double_quote_escapes: bool = True,
                    num_parsed_rows: int | None = None) -> type[Schema]:
    with open(path, newline="") as f:
        reader = _csv.reader(f, delimiter=delimiter, quotechar=quote)
        rows = []
        header = None
        for row in reader:
            if comment_character and row and row[0].startswith(comment_character):
                continue
            if header is None:
                header = row
                continue
            rows.append(row)
            if num_parsed_rows is not None and len(rows) >= num_parsed_rows:
                break
    assert header is not None, "empty csv"
    cols = {}
    for i, cname in enumerate(header):
        values = [r[i] for r in rows if i < len(r)]
        cols[cname] = ColumnSchema(name=cname, dtype=_infer_str_dtype(values))
    return schema_from_columns(cols, name=name)


def _infer_str_dtype(values: list[str]) -> dt.DType:
    def all_parse(fn):
        try:
            for v in values:
                fn(v)
            return True
        except ValueError:
            return False

    if not values:
        return dt.STR
    if all_parse(int):
        return dt.INT
    if all_parse(float):
        return dt.FLOAT
    if all(v.lower() in ("true", "false") for v in values):
        return dt.BOOL
    return dt.STR


def is_subschema(left, right) -> bool:
    ld, rd = left._dtypes(), right._dtypes()
    if set(ld) != set(rd):
        return False
    return all(dt.dtype_issubclass(ld[k], rd[k]) for k in ld)
