"""Lazy column expression AST.

Rebuild of the reference's expression system
(python/pathway/internals/expression.py:88-1160 and
src/engine/expression.rs). Expressions are built by operator overloading on
column references, carried as metadata on Tables, and compiled at lowering
time into *batched* evaluators (internals/expression_compiler.py) — columnar
numpy/JAX where dtypes allow, per-row Python only for object columns. UDFs
(`ApplyExpression`) are dispatched once per batch, never per row — the
design answer to the reference's per-row GIL re-entry
(dataflow.rs:1258-1318).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable

from pathway_tpu.internals import dtype as dt


class ColumnExpression:
    _dtype: dt.DType | None = None

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other):
        return BinaryExpression("+", self, other)

    def __radd__(self, other):
        return BinaryExpression("+", other, self)

    def __sub__(self, other):
        return BinaryExpression("-", self, other)

    def __rsub__(self, other):
        return BinaryExpression("-", other, self)

    def __mul__(self, other):
        return BinaryExpression("*", self, other)

    def __rmul__(self, other):
        return BinaryExpression("*", other, self)

    def __truediv__(self, other):
        return BinaryExpression("/", self, other)

    def __rtruediv__(self, other):
        return BinaryExpression("/", other, self)

    def __floordiv__(self, other):
        return BinaryExpression("//", self, other)

    def __rfloordiv__(self, other):
        return BinaryExpression("//", other, self)

    def __mod__(self, other):
        return BinaryExpression("%", self, other)

    def __rmod__(self, other):
        return BinaryExpression("%", other, self)

    def __pow__(self, other):
        return BinaryExpression("**", self, other)

    def __rpow__(self, other):
        return BinaryExpression("**", other, self)

    def __matmul__(self, other):
        return BinaryExpression("@", self, other)

    def __rmatmul__(self, other):
        return BinaryExpression("@", other, self)

    def __neg__(self):
        return UnaryExpression("-", self)

    def __invert__(self):
        return UnaryExpression("~", self)

    def __abs__(self):
        return MethodCallExpression("num.abs", self)

    # -- comparison --------------------------------------------------------
    def __eq__(self, other):  # type: ignore[override]
        return BinaryExpression("==", self, other)

    def __ne__(self, other):  # type: ignore[override]
        return BinaryExpression("!=", self, other)

    def __lt__(self, other):
        return BinaryExpression("<", self, other)

    def __le__(self, other):
        return BinaryExpression("<=", self, other)

    def __gt__(self, other):
        return BinaryExpression(">", self, other)

    def __ge__(self, other):
        return BinaryExpression(">=", self, other)

    # -- boolean (bitwise like pandas) ------------------------------------
    def __and__(self, other):
        return BinaryExpression("&", self, other)

    def __rand__(self, other):
        return BinaryExpression("&", other, self)

    def __or__(self, other):
        return BinaryExpression("|", self, other)

    def __ror__(self, other):
        return BinaryExpression("|", other, self)

    def __xor__(self, other):
        return BinaryExpression("^", self, other)

    def __rxor__(self, other):
        return BinaryExpression("^", other, self)

    def __hash__(self):
        return id(self)

    def __bool__(self):
        raise TypeError(
            "Cannot use a ColumnExpression in a boolean context — expressions "
            "are lazy; use & | ~ instead of and/or/not."
        )

    # -- access ------------------------------------------------------------
    def __getitem__(self, item):
        return GetExpression(self, item, check_if_exists=False)

    def get(self, item, default=None):
        return GetExpression(self, item, default=default, check_if_exists=True)

    # -- misc public combinators (parity with pw.ColumnExpression) ---------
    def is_none(self) -> "ColumnExpression":
        return IsNoneExpression(self)

    def is_not_none(self) -> "ColumnExpression":
        return IsNotNoneExpression(self)

    def as_int(self):
        return ConvertExpression(self, dt.INT)

    def as_float(self):
        return ConvertExpression(self, dt.FLOAT)

    def as_str(self):
        return ConvertExpression(self, dt.STR)

    def as_bool(self):
        return ConvertExpression(self, dt.BOOL)

    def to_string(self):
        return MethodCallExpression("to_string", self)

    def fill_error(self, replacement) -> "ColumnExpression":
        return FillErrorExpression(self, replacement)

    # namespaces
    @property
    def dt(self):
        from pathway_tpu.internals.expressions.date_time import DateTimeNamespace

        return DateTimeNamespace(self)

    @property
    def str(self):
        from pathway_tpu.internals.expressions.string import StringNamespace

        return StringNamespace(self)

    @property
    def num(self):
        from pathway_tpu.internals.expressions.numerical import NumericalNamespace

        return NumericalNamespace(self)

    # -- internals ---------------------------------------------------------
    @property
    def _deps(self) -> tuple["ColumnExpression", ...]:
        return ()

    def _to_internal(self) -> "ColumnExpression":
        return self

    def __repr__(self):
        from pathway_tpu.internals.expression_printer import print_expression

        return print_expression(self)


ExpressionLike = Any


def wrap_arg(arg: ExpressionLike) -> ColumnExpression:
    if isinstance(arg, ColumnExpression):
        return arg
    if isinstance(arg, ColumnNamespace):
        raise TypeError("namespace is not an expression")
    return ConstExpression(arg)


class ColumnNamespace:
    pass


class ConstExpression(ColumnExpression):
    def __init__(self, value: Any):
        self._value = value


class ColumnReference(ColumnExpression):
    """``table.colname`` / ``pw.this.colname``."""

    def __init__(self, table, name: str):
        self._table = table
        self._name = name

    @property
    def table(self):
        return self._table

    @property
    def name(self) -> str:
        return self._name

    def __call__(self, *args, **kwargs):
        raise TypeError(f"column {self._name!r} is not callable")


class IdExpression(ColumnReference):
    """``table.id`` — the key column."""

    def __init__(self, table):
        super().__init__(table, "id")


class BinaryExpression(ColumnExpression):
    def __init__(self, op: str, left: ExpressionLike, right: ExpressionLike):
        self._op = op
        self._left = wrap_arg(left)
        self._right = wrap_arg(right)

    @property
    def _deps(self):
        return (self._left, self._right)


class UnaryExpression(ColumnExpression):
    def __init__(self, op: str, arg: ExpressionLike):
        self._op = op
        self._arg = wrap_arg(arg)

    @property
    def _deps(self):
        return (self._arg,)


class IsNoneExpression(ColumnExpression):
    def __init__(self, arg):
        self._arg = wrap_arg(arg)

    @property
    def _deps(self):
        return (self._arg,)


class IsNotNoneExpression(ColumnExpression):
    def __init__(self, arg):
        self._arg = wrap_arg(arg)

    @property
    def _deps(self):
        return (self._arg,)


class IfElseExpression(ColumnExpression):
    def __init__(self, if_, then, else_):
        self._if = wrap_arg(if_)
        self._then = wrap_arg(then)
        self._else = wrap_arg(else_)

    @property
    def _deps(self):
        return (self._if, self._then, self._else)


class CoalesceExpression(ColumnExpression):
    def __init__(self, *args):
        self._args = tuple(wrap_arg(a) for a in args)

    @property
    def _deps(self):
        return self._args


class RequireExpression(ColumnExpression):
    """pw.require(val, *deps): val if all deps non-None else None."""

    def __init__(self, val, *args):
        self._val = wrap_arg(val)
        self._args = tuple(wrap_arg(a) for a in args)

    @property
    def _deps(self):
        return (self._val, *self._args)


class CastExpression(ColumnExpression):
    def __init__(self, return_type, expr):
        self._return_type = dt.wrap(return_type)
        self._expr = wrap_arg(expr)

    @property
    def _deps(self):
        return (self._expr,)


class ConvertExpression(ColumnExpression):
    """Runtime conversion (as_int/as_float/…, JSON unpacking)."""

    def __init__(self, expr, return_type, unwrap: bool = False):
        self._expr = wrap_arg(expr)
        self._return_type = dt.wrap(return_type)
        self._unwrap = unwrap

    @property
    def _deps(self):
        return (self._expr,)


class DeclareTypeExpression(ColumnExpression):
    def __init__(self, return_type, expr):
        self._return_type = dt.wrap(return_type)
        self._expr = wrap_arg(expr)

    @property
    def _deps(self):
        return (self._expr,)


class UnwrapExpression(ColumnExpression):
    def __init__(self, expr):
        self._expr = wrap_arg(expr)

    @property
    def _deps(self):
        return (self._expr,)


class FillErrorExpression(ColumnExpression):
    def __init__(self, expr, replacement):
        self._expr = wrap_arg(expr)
        self._replacement = wrap_arg(replacement)

    @property
    def _deps(self):
        return (self._expr, self._replacement)


class ApplyExpression(ColumnExpression):
    """Python UDF call — compiled to one *batched* host dispatch per delta."""

    def __init__(self, fn: Callable, return_type: Any, *args,
                 propagate_none: bool = False, deterministic: bool = True,
                 max_batch_size: int | None = None,
                 batch: bool = False, device: bool = False, **kwargs):
        self._fn = fn
        self._return_type = dt.wrap(return_type)
        self._args = tuple(wrap_arg(a) for a in args)
        self._kwargs = {k: wrap_arg(v) for k, v in kwargs.items()}
        self._propagate_none = propagate_none
        self._deterministic = deterministic
        self._max_batch_size = max_batch_size
        # batch=True → fn receives whole columns (lists) and returns a list:
        # the columnar dispatch path for TPU/vectorized UDFs (SURVEY §7 —
        # replaces the reference's per-row GIL calls, dataflow.rs:1300-1305)
        self._batch = batch
        # device=True (batch only) → the fn dispatches accelerator work:
        # the operator hosting this expression joins the scheduler's
        # pipelined device leg (engine/device_bridge.py)
        self._device = device and batch

    @property
    def _deps(self):
        return (*self._args, *self._kwargs.values())


class AsyncApplyExpression(ApplyExpression):
    """Async UDF — all rows of a batch awaited concurrently on one event
    loop (reference: async_apply_table, dataflow.rs:1454)."""


class FullyAsyncApplyExpression(ApplyExpression):
    """Non-blocking async UDF producing a Future column (pw.udf(executor=
    fully_async_executor)). Results arrive at later engine times."""

    def __init__(self, fn, return_type, *args, autocommit_duration_ms=1500, **kw):
        super().__init__(fn, return_type, *args, **kw)
        self._autocommit_duration_ms = autocommit_duration_ms


class ReducerExpression(ColumnExpression):
    def __init__(self, name: str, *args, **kwargs):
        self._name = name
        self._args = tuple(wrap_arg(a) for a in args)
        self._kwargs = kwargs

    @property
    def _deps(self):
        return self._args


class MethodCallExpression(ColumnExpression):
    """Namespace method (dt/str/num) — maps to a columnar kernel."""

    def __init__(self, method: str, *args, **kwargs):
        self._method = method
        self._args = tuple(wrap_arg(a) for a in args)
        self._kwargs = kwargs

    @property
    def _deps(self):
        return self._args


class PointerExpression(ColumnExpression):
    """pw.this.pointer_from(*args) — derive a key from values
    (reference: Expressions::PointerFrom + ShardPolicy.LastKeyColumn)."""

    def __init__(self, table, *args, optional: bool = False, instance=None):
        self._table = table
        self._args = tuple(wrap_arg(a) for a in args)
        self._optional = optional
        self._instance = wrap_arg(instance) if instance is not None else None

    @property
    def _deps(self):
        extra = (self._instance,) if self._instance is not None else ()
        return (*self._args, *extra)


class MakeTupleExpression(ColumnExpression):
    def __init__(self, *args):
        self._args = tuple(wrap_arg(a) for a in args)

    @property
    def _deps(self):
        return self._args


class GetExpression(ColumnExpression):
    def __init__(self, obj, index, default=None, check_if_exists=True):
        self._obj = wrap_arg(obj)
        self._index = wrap_arg(index)
        self._default = wrap_arg(default)
        self._check_if_exists = check_if_exists

    @property
    def _deps(self):
        return (self._obj, self._index, self._default)


# ---------------------------------------------------------------------------
# public helper constructors (pw.* level)
# ---------------------------------------------------------------------------

def if_else(if_: ExpressionLike, then: ExpressionLike, else_: ExpressionLike):
    return IfElseExpression(if_, then, else_)


def coalesce(*args: ExpressionLike):
    return CoalesceExpression(*args)


def require(val, *deps):
    return RequireExpression(val, *deps)


def cast(target_type, expr):
    return CastExpression(target_type, expr)


def declare_type(target_type, expr):
    return DeclareTypeExpression(target_type, expr)


def unwrap(expr):
    return UnwrapExpression(expr)


def fill_error(expr, replacement):
    return FillErrorExpression(expr, replacement)


def make_tuple(*args):
    return MakeTupleExpression(*args)


def apply(fn, *args, **kwargs):
    return ApplyExpression(fn, dt.ANY, *args, **kwargs)


def apply_with_type(fn, ret_type, *args, **kwargs):
    return ApplyExpression(fn, ret_type, *args, **kwargs)


def apply_async(fn, *args, **kwargs):
    return AsyncApplyExpression(fn, dt.ANY, *args, **kwargs)


def assert_table_has_columns(*a, **k):  # placed here for convenient re-export
    raise NotImplementedError


def walk(expr: ColumnExpression) -> Iterable[ColumnExpression]:
    yield expr
    for dep in expr._deps:
        yield from walk(dep)
