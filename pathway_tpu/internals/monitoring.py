"""Live monitoring dashboard.

Reference: python/pathway/internals/monitoring.py:56-226 — a rich-based
Live terminal dashboard showing per-connector/operator rows (insertions,
retractions, latency) above a rolling log panel, refreshed in place while
the pipeline runs, gated by ``MonitoringLevel``. Latency comes from the
scheduler's per-operator step probes (engine/graph.py Scheduler.stats,
the analogue of OperatorStats fed by Probers,
src/engine/progress_reporter.rs:114).
"""

from __future__ import annotations

import collections
import enum
import logging
import sys
import time


class MonitoringLevel(enum.Enum):
    AUTO = enum.auto()
    AUTO_ALL = enum.auto()
    NONE = enum.auto()
    IN_OUT = enum.auto()
    ALL = enum.auto()


def _log_buffer_lines(default: int = 8) -> int:
    """Log-panel depth, overridable with PATHWAY_LOG_BUFFER_LINES (a
    post-mortem dump in the log pane needs more than 8 lines)."""
    from pathway_tpu.internals.config import _env_int

    return max(1, _env_int("PATHWAY_LOG_BUFFER_LINES", default))


class _LogBuffer(logging.Handler):
    """Captures recent log records for the dashboard's log panel
    (reference keeps a rich log pane under the stats table)."""

    def __init__(self, maxlen: int | None = None):
        super().__init__()
        if maxlen is None:
            maxlen = _log_buffer_lines()
        self.records: collections.deque[str] = collections.deque(
            maxlen=maxlen)

    def emit(self, record):
        try:
            self.records.append(self.format(record))
        except Exception:
            pass


class StatsMonitor:
    """Collects per-operator counters + latency from the scheduler and
    renders a live terminal dashboard (rich Live on a tty, plain lines
    otherwise)."""

    def __init__(self, level: MonitoringLevel = MonitoringLevel.NONE,
                 refresh_seconds: float = 1.0):
        self.level = level
        self.refresh_seconds = refresh_seconds
        self._last_render = 0.0
        self._live = None
        self._rows: list[tuple] = []
        self._t0 = time.monotonic()
        # persistence driver (engine/persistence.py), set by the runtime:
        # the durability panel shows the commit watermark trailing the
        # pipeline before the lag ever becomes a stall
        self.persistence = None
        # connector supervision state (engine/supervisor.py) rendered as a
        # second panel: per-source lifecycle, restart counts, last error
        self.supervisor = None
        self._log = _LogBuffer()
        self._log.setFormatter(logging.Formatter("%(levelname)s %(message)s"))
        if self.enabled():
            logging.getLogger().addHandler(self._log)

    def set_supervisor(self, supervisor) -> None:
        self.supervisor = supervisor

    def enabled(self) -> bool:
        if self.level == MonitoringLevel.NONE:
            return False
        if self.level in (MonitoringLevel.AUTO, MonitoringLevel.AUTO_ALL):
            return sys.stderr.isatty()
        return True

    def _in_out_only(self) -> bool:
        return self.level in (MonitoringLevel.IN_OUT, MonitoringLevel.AUTO)

    def update(self, scheduler, graph, now_time: int) -> None:
        if not self.enabled():
            return
        now = time.monotonic()
        if now - self._last_render < self.refresh_seconds:
            return
        self._last_render = now
        self._rows = []
        # serving panel: per-request SLO snapshot from the run's request
        # tracker (engine/request_tracker.py) — query quantiles, burn
        # rate and the most recent over-budget request's dominant stage
        self._serving_lines = self._serving_panel(scheduler)
        # QoS panel: the control loop's side of the serving story —
        # budget partition, admission queue, shed/deferral/coalescing
        # (engine/qos.py)
        self._qos_line = self._qos_panel()
        # paged vector store line: page occupancy, free-list level and
        # growth events (engine/paged_store.py) — page churn and online
        # growth are visible without scraping /metrics
        self._paged_line = self._paged_panel()
        # semantic result cache line: hit ratio, entry count and the
        # incremental-invalidation counters (engine/result_cache.py)
        self._cache_line = self._cache_panel()
        # profiler line: rolling MFU / HBM bandwidth utilisation from the
        # device cost model plus the host sampler's hottest frame and its
        # own overhead ratio (engine/profiler.py)
        self._profiler_line = self._profiler_panel()
        # durability line: commit watermark, its lag behind the pipeline
        # head, and the bridge depth the last commit trailed — a frozen
        # watermark is visible here before the watchdog fires
        self._persistence_line = None
        if self.persistence is not None:
            pst = self.persistence.stats()
            self._persistence_line = (
                f"commit watermark t={pst['watermark']}  "
                f"lag {pst['lag_ticks']} tick(s)  "
                f"commits {pst['commits_with_data']}/{pst['commits']}  "
                f"inflight@commit {pst['inflight_at_commit']}  "
                f"wait {pst['commit_wait_ms_sum']:.0f}ms  "
                f"write-retries {pst['write_retries']}")
            if pst.get("snapshot_generation"):
                # snapshot tier: generation + age make a wedged snapshot
                # loop visible next to the (healthy) commit watermark
                self._persistence_line += (
                    f"  snap gen {pst['snapshot_generation']} "
                    f"t={pst['snapshot_tick']} "
                    f"age {pst['snapshot_age_ticks']}  "
                    f"wal {pst['wal_replayable_entries']} entr.")
        # pipelined-execution line: in-flight depth, dispatch-queue wait
        # and overlap ratio straight from the device bridge, so the
        # host/device overlap is observable, not inferred
        self._bridge_line = None
        bridge = scheduler.bridge_stats() \
            if hasattr(scheduler, "bridge_stats") else None
        if bridge is not None:
            self._bridge_line = (
                f"device bridge: in-flight {bridge['depth']}/"
                f"{bridge['max_inflight']}  legs {bridge['legs_resolved']}/"
                f"{bridge['legs_dispatched']}  "
                f"overlap {bridge['overlap_ratio']:.0%}  "
                f"queue-wait {bridge['queue_wait_ms']:.0f}ms  "
                f"exec {bridge['exec_ms']:.0f}ms")
        # fused-program dispatches (internals/autojit.py): the pipelining
        # panel shows whether the auto-jit tier is carrying batches and
        # on which backend — a demotion is visible here live
        try:
            from pathway_tpu.internals.autojit import autojit_stats

            ajs = autojit_stats()
        except Exception:
            ajs = None
        if ajs is not None and ajs["programs"]:
            line = (
                f"auto-jit: {ajs['programs']} fused program(s)  "
                f"xla {ajs['device_dispatches']} / "
                f"vector {ajs['vector_dispatches']} dispatches  "
                f"compiles {ajs['compiles']}  "
                f"demotions {ajs['demotions']}")
            self._bridge_line = (f"{self._bridge_line}\n{line}"
                                 if self._bridge_line else line)
        for node in graph.nodes:
            st = scheduler.stats.get(node.id)
            if not st:
                continue
            if self._in_out_only() and not node.name.startswith(
                    ("source", "subscribe", "capture", "output")):
                continue
            self._rows.append((node.name or str(node.id),
                               st["insertions"], st["retractions"],
                               st.get("latency_ms", 0.0),
                               st.get("total_ms", 0.0)))
        self._render(now_time)

    def _renderable(self, now_time: int):
        from rich.console import Group
        from rich.panel import Panel
        from rich.table import Table as RichTable

        elapsed = time.monotonic() - self._t0
        table = RichTable(
            title=f"pathway-tpu  t={now_time}  up {elapsed:5.1f}s")
        table.add_column("operator")
        table.add_column("insertions", justify="right")
        table.add_column("retractions", justify="right")
        table.add_column("latency ms", justify="right")
        table.add_column("total ms", justify="right")
        for name, ins, rets, lat, tot in self._rows:
            table.add_row(name, str(ins), str(rets), f"{lat:.2f}",
                          f"{tot:.0f}")
        parts = [table]
        slow = self._slowest_lines()
        if slow:
            parts.append(Panel("\n".join(slow), title="top slowest (last tick)",
                               height=None))
        if getattr(self, "_bridge_line", None):
            parts.append(Panel(self._bridge_line, title="pipelining",
                               height=None))
        if getattr(self, "_persistence_line", None):
            parts.append(Panel(self._persistence_line, title="durability",
                               height=None))
        if getattr(self, "_paged_line", None):
            parts.append(Panel(self._paged_line, title="paged store",
                               height=None))
        if getattr(self, "_cache_line", None):
            parts.append(Panel(self._cache_line, title="result cache",
                               height=None))
        if getattr(self, "_profiler_line", None):
            parts.append(Panel(self._profiler_line, title="profiler",
                               height=None))
        if getattr(self, "_serving_lines", None):
            parts.append(Panel("\n".join(self._serving_lines),
                               title="serving", height=None))
        if getattr(self, "_qos_line", None):
            parts.append(Panel(self._qos_line, title="qos", height=None))
        sup_lines = self._supervisor_lines()
        if sup_lines:
            parts.append(Panel("\n".join(sup_lines), title="connectors",
                               height=None))
        if self._log.records:
            parts.append(Panel("\n".join(self._log.records), title="log",
                               height=None))
        return parts[0] if len(parts) == 1 else Group(*parts)

    def _serving_panel(self, scheduler) -> list[str]:
        rec = getattr(scheduler, "recorder", None)
        tracker = rec.requests if rec is not None and rec.enabled else None
        if tracker is None or not tracker.count:
            return []
        s = tracker.summary()
        lines = []
        e2e = s.get("e2e_ms")
        if e2e:
            lines.append(
                f"queries {s['requests']}  p50 {e2e['p50']:.1f}ms  "
                f"p95 {e2e['p95']:.1f}ms  p99 {e2e['p99']:.1f}ms  "
                f"SLO {s['slo_ms']:.0f}ms  burn {s['burn_rate']:.2f}x  "
                f"over-budget {s['violations']}")
        stages = s.get("stages")
        if stages:
            lines.append("stage p50: " + "  ".join(
                f"{name} {v:.1f}ms" for name, v in stages.items()
                if v is not None))
        slow = tracker.slow_queries()
        if slow:
            last = slow[-1]
            lines.append(
                f"slow: {last['request_id']} {last['e2e_ms']:.1f}ms "
                f"dominant {last['dominant_stage']} "
                f"({last['stages'][last['dominant_stage']]:.1f}ms)")
        return lines

    def _qos_panel(self) -> str | None:
        try:
            from pathway_tpu.engine.qos import current_controller

            ctl = current_controller()
        except Exception:
            return None
        if ctl is None:
            return None
        s = ctl.summary()
        line = (f"{s['mode']}: query budget {s['query_budget_ms']:.1f}ms  "
                f"ingest {s['ingest_rows_per_tick']} rows/tick  "
                f"queue {s['admission_queue_depth']}/"
                f"{s['admission_queue_cap']}  shed {s['shed_total']}  "
                f"deferrals {s['ingest_deferrals']}  "
                f"coalesced {s['coalesced_queries']}q/"
                f"{s['coalesced_dispatches']}d")
        if s["shedding"]:
            line += "  SHEDDING"
        if s["backpressure_active"]:
            line += "  backpressure"
        return line

    def _paged_panel(self) -> str | None:
        try:
            from pathway_tpu.engine.paged_store import live_paged_stats

            st = live_paged_stats()
        except Exception:
            return None
        if st is None:
            return None
        line = (f"pages {st['pages_total'] - st['pages_free']}/"
                f"{st['pages_total']} x {st['page_rows']} rows  "
                f"occupancy {st['occupancy']:.0%}  "
                f"extents {st['extents']}  grows {st['grow_events']}")
        if st["tenants"]:
            line += "  tenants " + " ".join(
                f"{t}:{n}p" for t, n in sorted(st["tenants"].items()))
        return line

    def _cache_panel(self) -> str | None:
        try:
            from pathway_tpu.engine.result_cache import live_cache_stats

            st = live_cache_stats()
        except Exception:
            return None
        if st is None:
            return None
        return (f"entries {st['entries']}  "
                f"hit {st['hit_ratio']:.0%} ({st['hits']}h/{st['misses']}m)"
                f"  invalidations {st['invalidations']} "
                f"({st['invalidations_per_tick']:.2f}/tick)  "
                f"v{st['version']}")

    def _profiler_panel(self) -> str | None:
        try:
            from pathway_tpu.engine.profiler import live_profiler_stats

            st = live_profiler_stats()
        except Exception:
            return None
        if st is None:
            return None
        line = (f"MFU {st['mfu_rolling']:.1%}  "
                f"HBM {st['hbm_bw_util']:.1%}  "
                f"samples {st['host']['samples_total']} "
                f"({st['host']['device_attributed_samples']} on-device)  "
                f"overhead {st['host']['overhead_ratio']:.2%}")
        top = st["host"].get("top_frame")
        if top:
            line += f"\nhot: {top}"
        fams = st.get("families") or {}
        bound = [f"{name}:{fam['roofline']['bound_by'][:4]}"
                 for name, fam in sorted(fams.items()) if fam["dispatches"]]
        if bound:
            line += "\nroofline " + "  ".join(bound)
        return line

    def _slowest_lines(self, top_n: int = 5) -> list[str]:
        """Critical-path panel: the operators that dominated the last
        tick, worst first — the per-tick answer to "where does the time
        go" (stats latency_ms is each operator's last step latency)."""
        ranked = sorted(self._rows, key=lambda r: r[3], reverse=True)
        total = sum(r[3] for r in self._rows) or 1.0
        lines = []
        for name, _ins, _rets, lat, _tot in ranked[:top_n]:
            if lat <= 0.0:
                break
            lines.append(f"{name}: {lat:.2f}ms ({lat / total:.0%} of tick)")
        return lines

    def _supervisor_lines(self) -> list[str]:
        if self.supervisor is None:
            return []
        lines = []
        for s in self.supervisor.summary():
            line = (f"{s['source']}: {s['state']}  rows={s['forwarded']}  "
                    f"restarts={s['restarts']}")
            if s["restarts"] and s.get("last_restart_age_s") is not None:
                line += f" (last {s['last_restart_age_s']:.0f}s ago)"
            if s["stalled"]:
                line += "  STALLED"
            if s["error"]:
                line += f"  last_error={s['error']}"
            lines.append(line)
        if self.supervisor.commit_stalled:
            lines.append("COMMIT LOOP STALLED (watchdog)")
        return lines

    def _render(self, now_time: int) -> None:
        try:
            if self._live is None:
                from rich.console import Console
                from rich.live import Live

                self._live = Live(self._renderable(now_time),
                                  console=Console(stderr=True),
                                  refresh_per_second=4, transient=False)
                self._live.start()
            else:
                self._live.update(self._renderable(now_time))
        except Exception:
            for name, ins, rets, lat, tot in self._rows:
                print(f"[monitor] {name}: +{ins} -{rets} {lat:.2f}ms",
                      file=sys.stderr)
            if getattr(self, "_bridge_line", None):
                print(f"[monitor] {self._bridge_line}", file=sys.stderr)
            if getattr(self, "_persistence_line", None):
                print(f"[monitor] {self._persistence_line}", file=sys.stderr)
            if getattr(self, "_paged_line", None):
                print(f"[monitor] {self._paged_line}", file=sys.stderr)
            if getattr(self, "_cache_line", None):
                print(f"[monitor] {self._cache_line}", file=sys.stderr)
            if getattr(self, "_profiler_line", None):
                print(f"[monitor] {self._profiler_line}", file=sys.stderr)
            for line in getattr(self, "_serving_lines", None) or ():
                print(f"[monitor] {line}", file=sys.stderr)
            if getattr(self, "_qos_line", None):
                print(f"[monitor] {self._qos_line}", file=sys.stderr)
            for line in self._supervisor_lines():
                print(f"[monitor] {line}", file=sys.stderr)

    def close(self) -> None:
        if self._live is not None:
            try:
                self._live.stop()
            except Exception:
                pass
            self._live = None
        if self.enabled():
            logging.getLogger().removeHandler(self._log)
