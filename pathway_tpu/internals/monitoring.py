"""Monitoring dashboard (reference: python/pathway/internals/monitoring.py —
rich-based live operator stats table + MonitoringLevel)."""

from __future__ import annotations

import enum
import sys
import time


class MonitoringLevel(enum.Enum):
    AUTO = enum.auto()
    AUTO_ALL = enum.auto()
    NONE = enum.auto()
    IN_OUT = enum.auto()
    ALL = enum.auto()


class StatsMonitor:
    """Collects per-operator counters from the scheduler and renders a
    terminal dashboard (rich if a tty, plain lines otherwise)."""

    def __init__(self, level: MonitoringLevel = MonitoringLevel.NONE,
                 refresh_seconds: float = 1.0):
        self.level = level
        self.refresh_seconds = refresh_seconds
        self._last_render = 0.0
        self._live = None
        self._rows: list[tuple] = []

    def enabled(self) -> bool:
        if self.level == MonitoringLevel.NONE:
            return False
        if self.level in (MonitoringLevel.AUTO, MonitoringLevel.AUTO_ALL):
            return sys.stderr.isatty()
        return True

    def update(self, scheduler, graph, now_time: int) -> None:
        if not self.enabled():
            return
        now = time.monotonic()
        if now - self._last_render < self.refresh_seconds:
            return
        self._last_render = now
        self._rows = []
        for node in graph.nodes:
            st = scheduler.stats.get(node.id)
            if not st:
                continue
            if self.level in (MonitoringLevel.IN_OUT, MonitoringLevel.AUTO):
                if not (node.name.startswith(("source", "subscribe", "capture",
                                              "output"))):
                    continue
            self._rows.append((node.name or str(node.id),
                               st["insertions"], st["retractions"]))
        self._render(now_time)

    def _render(self, now_time: int) -> None:
        try:
            from rich.console import Console
            from rich.table import Table as RichTable

            console = Console(stderr=True)
            table = RichTable(title=f"pathway-tpu @ t={now_time}")
            table.add_column("operator")
            table.add_column("insertions", justify="right")
            table.add_column("retractions", justify="right")
            for name, ins, rets in self._rows:
                table.add_row(name, str(ins), str(rets))
            console.print(table)
        except Exception:
            for name, ins, rets in self._rows:
                print(f"[monitor] {name}: +{ins} -{rets}", file=sys.stderr)
