"""Experimental interactive (REPL) mode
(reference: internals/interactive.py:181-222 — a displayhook that renders
live tables as strings, plus enable/is_enabled controllers).

In this build, displaying a Table in interactive mode computes a bounded
snapshot through the engine and prints it (the reference's LiveTable
auto-refresh thread is tied to its monitoring stack; bounded preview is the
capability REPL users rely on)."""

from __future__ import annotations

import sys
import warnings
from typing import Callable


class DisplayAsStr:
    """Marker: the interactive displayhook prints str(value) for these."""


class InteractiveModeController:
    _orig_displayhook: Callable[[object], None]

    def __init__(self, _pathway_internal: bool = False) -> None:
        assert _pathway_internal, (
            "InteractiveModeController is an internal class")
        self._orig_displayhook = sys.displayhook
        sys.displayhook = self._displayhook

    def _displayhook(self, value: object) -> None:
        from pathway_tpu.internals.table import Table

        if isinstance(value, DisplayAsStr):
            import builtins

            builtins._ = value
            print(str(value))
        elif isinstance(value, Table):
            import builtins

            builtins._ = value
            try:
                from pathway_tpu.debug import table_to_markdown

                print(table_to_markdown(value))
            except Exception as e:
                print(f"<Table: preview unavailable: {e}>")
        else:
            self._orig_displayhook(value)

    def close(self) -> None:
        sys.displayhook = self._orig_displayhook


def is_interactive_mode_enabled() -> bool:
    from pathway_tpu.internals.parse_graph import G

    return getattr(G, "interactive_mode_controller", None) is not None


def enable_interactive_mode() -> InteractiveModeController:
    warnings.warn("interactive mode is experimental", stacklevel=2)
    from pathway_tpu.internals.parse_graph import G

    controller = getattr(G, "interactive_mode_controller", None)
    if controller is not None:
        return controller
    controller = InteractiveModeController(_pathway_internal=True)
    G.interactive_mode_controller = controller
    return controller


class LiveTable:
    """Interactive-mode live view of a table (reference:
    internals/interactive.py LiveTable — a REPL-refreshed snapshot).
    Construct via ``enable_interactive_mode()`` + ``LiveTable.create``."""

    def __init__(self, table, controller=None):
        self.table = table
        self.controller = controller

    @classmethod
    def create(cls, table, controller=None):
        return cls(table, controller)

    def snapshot(self):
        from pathway_tpu.debug import table_to_pandas

        return table_to_pandas(self.table)

    def _repr_html_(self):  # notebook display hook
        return self.snapshot().to_html()
