"""API-compatibility surface: names the reference exports at top level
whose machinery lives elsewhere in this build (reference:
python/pathway/__init__.py __all__ — aliases, assertion helpers, the
py-object wrapper, free-function join forms).
"""

from __future__ import annotations

import contextlib
import pickle
from dataclasses import dataclass, field
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as sch
from pathway_tpu.internals.table import Table


# -- py-object wrapper (reference: internals/api.py wrap_py_object) ---------

@dataclass(frozen=True)
class PyObjectWrapper:
    """Opaque wrapper carrying an arbitrary Python object through the
    engine (reference: api.PyObjectWrapper — there it crosses the Rust
    boundary; here values are host-native, so the wrapper is the
    API-stable envelope + serializer hook)."""

    value: Any
    _serializer: Any = field(default=None, compare=False, repr=False)

    def dumps(self) -> bytes:
        if self._serializer is not None:
            return self._serializer.dumps(self.value)
        return pickle.dumps(self.value)


def wrap_py_object(object: Any, *, serializer=None) -> PyObjectWrapper:
    return PyObjectWrapper(object, serializer)


# -- iterate_universe marker (reference: internals/operator.py:309) ---------

@dataclass(frozen=True)
class iterate_universe:  # noqa: N801 — reference-parity name
    """Marks an iterate() input whose UNIVERSE (key set) iterates while
    its column values come along for the ride."""

    table: Table


# -- schema assertion (reference: internals/common.py:474) ------------------

def assert_table_has_schema(table: Table, schema: type[sch.Schema], *,
                            allow_superset: bool = True,
                            ignore_primary_keys: bool = True,
                            allow_subtype: bool = True) -> None:
    """Assert the table's schema is equivalent to ``schema``."""
    tcols = dict(table.schema._dtypes())
    scols = dict(schema._dtypes())
    if not allow_superset and set(tcols) - set(scols):
        raise AssertionError(
            f"table has extra columns {sorted(set(tcols) - set(scols))}")
    missing = set(scols) - set(tcols)
    if missing:
        raise AssertionError(f"table lacks columns {sorted(missing)}")
    for name, want in scols.items():
        got = tcols[name]
        if got == want or want is dt.ANY:
            continue
        if allow_subtype and dt.unoptionalize(got) == dt.unoptionalize(want):
            continue
        raise AssertionError(
            f"column {name!r}: table has {got}, schema wants {want}")
    if not ignore_primary_keys:
        if list(table.schema.primary_key_columns() or []) != \
                list(schema.primary_key_columns() or []):
            raise AssertionError("primary keys differ")


# -- error logs (reference: internals/errors.py local_error_log) ------------

@contextlib.contextmanager
def local_error_log():
    """Scope-local error log: operators BUILT inside the ``with`` block
    report their errors here — including errors raised later, at run
    time, while those operators step (Plan stamps the scope's log; the
    scheduler activates it around each stamped node's step — the
    reference's per-scope error-log tables, graph.rs error_log APIs)."""
    from pathway_tpu.internals import error as err

    local = err.ErrorLog()
    err.push_construction_log(local)
    try:
        yield local
    finally:
        err.pop_construction_log()


# -- monitoring config (reference: internals/config.py:144) -----------------

_monitoring_endpoint: dict = {"server_endpoint": None}


def set_monitoring_config(*, server_endpoint: str | None) -> None:
    """Point OpenTelemetry exports at an OTLP endpoint
    (internals/telemetry.py reads this when building its config)."""
    _monitoring_endpoint["server_endpoint"] = server_endpoint


def get_monitoring_endpoint() -> str | None:
    return _monitoring_endpoint["server_endpoint"]


# -- engine type facade (reference: api.PathwayType re-exported as Type) ----

class Type:
    """Static engine types, reference ``pw.Type`` (engine.pyi PathwayType):
    ``pw.Type.STRING`` etc., plus the composite constructors."""

    ANY = dt.ANY
    STRING = dt.STR
    INT = dt.INT
    FLOAT = dt.FLOAT
    BOOL = dt.BOOL
    POINTER = dt.POINTER
    BYTES = dt.BYTES
    DATE_TIME_NAIVE = dt.DATE_TIME_NAIVE
    DATE_TIME_UTC = dt.DATE_TIME_UTC
    DURATION = dt.DURATION
    JSON = dt.JSON
    ARRAY = dt.ANY_ARRAY
    INT_ARRAY = dt.INT_ARRAY
    FLOAT_ARRAY = getattr(dt, "FLOAT_ARRAY", dt.ANY_ARRAY)
    PY_OBJECT_WRAPPER = dt.ANY

    @staticmethod
    def optional(arg):
        return dt.Optional(arg)

    @staticmethod
    def tuple(*args):
        return dt.Tuple(*args)

    @staticmethod
    def list(arg):
        return getattr(dt, "List", lambda a: dt.ANY)(arg)

    @staticmethod
    def array(n_dim=None, wrapped=None):
        return dt.ANY_ARRAY


# -- joinable/table-like bases (reference: Joinable ⊃ Table, JoinResult) ----

import abc  # noqa: E402


class TableLike(abc.ABC):
    """Things carrying a universe (reference internals/table_like.py)."""


class Joinable(TableLike):
    """Things a join can take as a side (reference internals/joins.py)."""


def _register_bases() -> None:
    from pathway_tpu.internals.groupbys import GroupedTable
    from pathway_tpu.internals.joins import JoinResult

    for cls in (Table, JoinResult):
        Joinable.register(cls)
    for cls in (Table, JoinResult, GroupedTable):
        TableLike.register(cls)


_register_bases()


# -- free-function join forms (reference exports join/join_inner/...) -------

def join(left: Table, right: Table, *on, how: str = "inner", **kwargs):
    return left.join(right, *on, how=how, **kwargs)


def join_inner(left: Table, right: Table, *on, **kwargs):
    return left.join(right, *on, how="inner", **kwargs)


def join_left(left: Table, right: Table, *on, **kwargs):
    return left.join(right, *on, how="left", **kwargs)


def join_right(left: Table, right: Table, *on, **kwargs):
    return left.join(right, *on, how="right", **kwargs)


def join_outer(left: Table, right: Table, *on, **kwargs):
    return left.join(right, *on, how="outer", **kwargs)
